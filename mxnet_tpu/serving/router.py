"""Serving-fleet router: least-loaded balancing over N replicas with
draining rolling upgrades.

PR 6 built ONE continuous-batching server process; this module turns N
of them into a fleet (ROADMAP item 2).  A stdlib-HTTP router process
(``tools/serve.py --router``) owns the replica registry and fronts
``POST /generate``:

- **registry** — a static list (``MXTPU_SERVE_REPLICAS``, comma-
  separated ``host:port``) and/or self-registration through the PR-13
  coordinator: replicas join with ``role="serve"`` (``tools/serve.py
  --register``; :func:`register_replica`), hold the same heartbeat
  lease training hosts do, and the router folds ``GET /cluster``
  members into its replica set each sweep — a SIGKILLed replica's
  lease expires and it leaves the registry without operator action.
- **balancing** — each replica's existing ``/healthz`` ``{slots,
  occupied, queue_depth, queue_size, draining}`` is scraped every
  ``MXTPU_ROUTER_SCRAPE_S`` on a background thread (pure host-side
  HTTP; declared in ``analysis/config.py:ENTRY_POINTS``) and cached;
  ``/generate`` goes to the least-loaded live replica
  (``(occupied + queue_depth) / slots``).
- **retries** — failures where the replica provably did no generation
  work (connection refused / connect-stage errors, 429 queue-full,
  503 draining) re-route to the next replica, bounded by
  ``MXTPU_ROUTER_RETRIES`` and counted in
  ``router_retries_total{reason}``; exhaustion raises the named
  :class:`RouterRetriesExhausted`.  A connection that breaks AFTER the
  request was accepted is NOT idempotent (tokens may have been
  generated and delivered nowhere) — it returns the named
  :class:`ReplicaDied` as an HTTP 502 naming the replica; a replica
  that merely exceeds ``generate_timeout_s`` returns the named
  :class:`ReplicaTimeout` as an HTTP 504 and is NOT marked dead.
- **backpressure** — 503 + ``Retry-After`` whenever EVERY replica is
  draining or full — including when every re-route attempt was shed
  with a live 429/503; a single sick replica never surfaces to
  clients.
- **rolling upgrade** — ``POST /admin/drain`` fans out (or targets one
  replica); :meth:`ReplicaRouter.rolling_upgrade` drains one replica,
  waits ``drained``, restarts it, un-drains, then moves to the next —
  the fleet upgrades under live traffic (runbook: docs/serving.md).

- **tracing + SLO** (ISSUE 16) — the router is where traces are born
  and where the SLO plane lives: ``POST /generate`` adopts the
  client's ``traceparent`` (or mints one — ``telemetry/tracing.py``),
  forwards the SAME trace id on every re-route attempt under a fresh
  parent span id, answers with ``X-MXTPU-Trace``, and feeds every
  terminal outcome into :class:`~mxnet_tpu.telemetry.tracing.SloPlane`
  — multi-window burn rates at ``GET /slo``, span buffer at
  ``GET /spans.json``, per-trace join via ``fleetstat.py trace <id>``.

``GET /fleet`` serves the router's federation view — per-replica health
rows plus the replicas' ``/metrics.json`` merged host-labeled through
:func:`telemetry.fleet.merge_snapshots` — rendered by
``tools/fleetstat.py --router``.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time

from .. import telemetry as _tm
from ..base import MXNetError
from ..telemetry import fleet as _fleet
from ..telemetry import tracing as _tracing

__all__ = ["ReplicaRouter", "start_router", "register_replica",
           "RouterRetriesExhausted", "NoReplicaAvailable", "ReplicaDied",
           "ReplicaTimeout", "router_scrape_s", "router_retries"]

_logger = logging.getLogger("mxnet_tpu.serving.router")

# --- router metric families (docs/telemetry.md, serving-fleet section) ------
_TM_ROUTED = _tm.counter(
    "router_requests_total",
    "requests routed by terminal outcome: relayed (a replica answered — "
    "whatever its status), unavailable (every replica draining/full, "
    "HTTP 503), exhausted (MXTPU_ROUTER_RETRIES re-routes all failed, "
    "502), dead (replica died mid-request, 502), timeout (replica "
    "exceeded generate_timeout_s, 504 — not marked dead)",
    labels=("outcome",))
_TM_RETRIES = _tm.counter(
    "router_retries_total",
    "idempotent re-routes to the next replica by reason: connect "
    "(connection-stage failure, no work started), draining (503), "
    "full (429)", labels=("reason",))
_TM_REPLICAS = _tm.gauge(
    "router_replicas",
    "replica registry by state: healthy (routable), draining "
    "(finishing in-flight work), dead (healthz unreachable)",
    labels=("state",))
_TM_PROXY_SEC = _tm.histogram(
    "router_request_seconds",
    "end-to-end routed /generate latency through the router, retries "
    "included")


class NoReplicaAvailable(MXNetError):
    """Every registered replica is draining, full, or dead — shed load
    (HTTP 503 + Retry-After)."""


class RouterRetriesExhausted(MXNetError):
    """Every idempotent re-route failed: MXTPU_ROUTER_RETRIES+1
    replicas were tried and none accepted the request (HTTP 502)."""


class ReplicaDied(MXNetError):
    """The connection broke AFTER a replica accepted the request —
    generation may have happened, so the router must NOT silently
    retry; the client decides (HTTP 502 naming the replica)."""


class ReplicaTimeout(MXNetError):
    """The replica accepted the request but did not answer within
    ``generate_timeout_s`` — slow, not provably dead: the router
    neither retries (generation may still be running) nor marks the
    replica dead (HTTP 504 naming the replica)."""


def router_scrape_s() -> float:
    """``MXTPU_ROUTER_SCRAPE_S`` — replica /healthz scrape interval
    (default 1s; the routing signal's staleness bound)."""
    try:
        return max(float(os.environ.get("MXTPU_ROUTER_SCRAPE_S", "1")),
                   0.05)
    except ValueError:
        return 1.0


def router_retries() -> int:
    """``MXTPU_ROUTER_RETRIES`` — bounded idempotent re-routes per
    request after the first attempt (default 2)."""
    try:
        return max(int(os.environ.get("MXTPU_ROUTER_RETRIES", "2")), 0)
    except ValueError:
        return 2


def replicas_from_env():
    """``MXTPU_SERVE_REPLICAS`` — static ``host:port`` list."""
    raw = os.environ.get("MXTPU_SERVE_REPLICAS", "")
    return [a.strip() for a in raw.split(",") if a.strip()]


def register_replica(serve_addr, coordinator=None, member=None):
    """Self-register a serving replica with the PR-13 coordinator
    (``role="serve"``): the replica holds a heartbeat lease like any
    training host, routers discover it from ``GET /cluster``, and its
    death expires the lease instead of needing operator action.
    ``serve_addr`` doubles as the health/metrics endpoint (one port
    serves /generate, /healthz and /metrics).  Returns the
    CoordinatorClient (call ``.leave()`` on clean shutdown)."""
    from ..parallel.coordinator import CoordinatorClient, coord_addr

    addr = coordinator or coord_addr()
    if not addr:
        raise MXNetError(
            "no coordinator address: pass coordinator= or set "
            "MXTPU_COORD_ADDR")
    member = member or f"serve:{socket.gethostname()}:{os.getpid()}"
    return CoordinatorClient(addr, member=member, rank=-1,
                             telemetry_addr=str(serve_addr), role="serve")


class ReplicaRouter:
    """The replica registry + least-loaded balancer.

    ``replicas``: static ``host:port`` list (default:
    ``MXTPU_SERVE_REPLICAS``).  ``coordinator``: ``host:port`` of a
    PR-13 coordinator whose ``role="serve"`` members join the registry
    dynamically.  ``start()`` launches the background health scrape;
    :func:`start_router` adds the HTTP front-end.
    """

    def __init__(self, replicas=None, coordinator=None, scrape_s=None,
                 retries=None, generate_timeout_s=300.0):
        static = list(replicas) if replicas is not None \
            else replicas_from_env()
        self.coordinator = coordinator
        if not static and not coordinator:
            raise MXNetError(
                "router needs replicas: set MXTPU_SERVE_REPLICAS or "
                "pass a coordinator address for self-registration")
        self.scrape_s = (router_scrape_s() if scrape_s is None
                         else float(scrape_s))
        self.retries = router_retries() if retries is None \
            else int(retries)
        self.generate_timeout_s = float(generate_timeout_s)
        # the SLO plane lives at the router: it sees every request's
        # terminal outcome, replicas only see their own (GET /slo)
        self.slo = _tracing.SloPlane()
        self._lock = threading.Lock()
        self._replicas = {}
        for addr in static:
            self._replicas[addr] = self._new_row(addr, "static")
        self._stop = threading.Event()
        self._thread = None

    @staticmethod
    def _new_row(addr, source):
        return {"addr": addr, "source": source, "ok": False,
                "draining": False, "health": None, "error": None,
                "at": 0.0}

    # ------------------------------------------------------------- registry
    def _coordinator_members(self):
        """Current ``role="serve"`` members' advertised endpoints (the
        self-registration half of the registry)."""
        cl = _fleet.fetch_json(self.coordinator, "/cluster",
                               timeout=min(self.scrape_s * 2, 5.0))
        return {m["telemetry"] for m in (cl.get("members") or {}).values()
                if m.get("role") == "serve" and m.get("telemetry")}

    def scrape_once(self):
        """One registry sweep: fold in coordinator-registered replicas,
        then re-scrape every replica's /healthz into the routing cache.
        Pure host-side HTTP — an ENTRY_POINTS steady-state loop; one
        dead replica costs a bounded timeout, never the sweep."""
        if self.coordinator:
            try:
                seen = self._coordinator_members()
            except Exception as exc:  # noqa: BLE001 — a dead coordinator
                #   degrades discovery, never routing over known replicas
                _logger.warning("router: coordinator %s unreachable: %r",
                                self.coordinator, exc)
            else:
                with self._lock:
                    for addr in seen:
                        if addr not in self._replicas:
                            self._replicas[addr] = self._new_row(
                                addr, "coordinator")
                            _logger.info(
                                "router: replica %s joined via "
                                "coordinator", addr)
                    for addr in [a for a, r in self._replicas.items()
                                 if r["source"] == "coordinator"
                                 and a not in seen]:
                        del self._replicas[addr]
                        _logger.warning(
                            "router: replica %s left the coordinator "
                            "registry", addr)
        with self._lock:
            addrs = list(self._replicas)
        for addr in addrs:
            try:
                hz = _fleet.fetch_json(addr, "/healthz",
                                       timeout=min(self.scrape_s, 2.0))
                row = {"ok": True, "error": None, "health": hz,
                       "draining": bool(hz.get("draining")
                                        or hz.get("status")
                                        in ("draining", "drained")),
                       "at": time.time()}
            except Exception as exc:  # noqa: BLE001 — dead replica =
                #                       one row marked dead, sweep lives
                row = {"ok": False, "error": repr(exc), "health": None,
                       "draining": False, "at": time.time()}
            with self._lock:
                if addr in self._replicas:
                    self._replicas[addr].update(row)
        self._set_gauges()
        # refresh slo_burn_rate{objective,window} each sweep, so the
        # gauges decay with the trailing windows without /slo polling
        self.slo.snapshot()
        return self.replicas()

    def _set_gauges(self):
        with self._lock:
            rows = list(self._replicas.values())
        _TM_REPLICAS.set(sum(1 for r in rows if r["ok"]
                             and not r["draining"]), state="healthy")
        _TM_REPLICAS.set(sum(1 for r in rows if r["ok"]
                             and r["draining"]), state="draining")
        _TM_REPLICAS.set(sum(1 for r in rows if not r["ok"]),
                         state="dead")

    def replicas(self):
        """Registry snapshot: addr -> cached health row."""
        with self._lock:
            return {a: dict(r) for a, r in self._replicas.items()}

    # ------------------------------------------------------------ balancing
    @staticmethod
    def _full(hz):
        slots = int(hz.get("slots") or 0)
        if slots < 1:
            return True
        if int(hz.get("occupied") or 0) < slots:
            return False
        return int(hz.get("queue_depth") or 0) >= \
            int(hz.get("queue_size", 1 << 30))

    def pick(self, exclude=()):
        """The least-loaded live replica ((occupied + queue_depth) /
        slots over the cached healthz), or None when every replica is
        draining, full, dead, or excluded."""
        with self._lock:
            best, best_load = None, None
            for addr, row in self._replicas.items():
                if addr in exclude or not row["ok"] or row["draining"]:
                    continue
                hz = row["health"] or {}
                if self._full(hz):
                    continue
                load = (int(hz.get("occupied") or 0)
                        + int(hz.get("queue_depth") or 0)) \
                    / max(int(hz.get("slots") or 1), 1)
                if best_load is None or load < best_load:
                    best, best_load = addr, load
            return best

    def _mark_dead(self, addr, exc):
        with self._lock:
            row = self._replicas.get(addr)
            if row is not None:
                row.update(ok=False, error=repr(exc), health=None,
                           at=time.time())

    def route_generate(self, body: bytes, traceparent=None):
        """Forward one /generate body to the least-loaded replica,
        re-routing idempotent failures; returns ``(status, payload
        bytes, replica addr)``.  Raises :class:`NoReplicaAvailable`
        (503), :class:`RouterRetriesExhausted` (502),
        :class:`ReplicaDied` (502) or :class:`ReplicaTimeout` (504).

        ``traceparent``: the client's W3C header — absent or malformed
        degrades to a freshly minted trace, never an error.  Every
        (re-)route attempt forwards the SAME trace id under a fresh
        parent span id (the replica's spans parent the router's
        attempt span exactly), and the terminal outcome feeds the SLO
        plane: availability = relayed without a 5xx/transport failure,
        TTFT read from the replica's reply."""
        import http.client

        ctx = _tracing.parse_traceparent(traceparent) or \
            _tracing.parse_traceparent(_tracing.mint_traceparent())
        trace, sampled = ctx["trace"], ctx["sampled"]
        traced = sampled and _tracing.trace_on()
        route_sid = _tracing.mint_span_id()
        t0 = time.perf_counter()
        tried = set()
        last_error = None
        attempts = 0
        slo_ok = False        # flips only on a relayed non-5xx
        slo_ttft = None
        shed_only = True      # every failure so far was a live 429/503

        def _span_attempt(t_att, att_sid, addr, status):
            if traced:
                _tracing.record_span(
                    "attempt", "router", trace,
                    time.perf_counter() - t_att, parent=route_sid,
                    span=att_sid, replica=addr, status=status,
                    attempt=attempts)

        try:
            for _ in range(self.retries + 1):
                addr = self.pick(exclude=tried)
                if addr is None:
                    break
                attempts += 1
                host, port = addr.rsplit(":", 1)
                conn = http.client.HTTPConnection(
                    host, int(port), timeout=self.generate_timeout_s)
                accepted = False
                att_sid = _tracing.mint_span_id()
                t_att = time.perf_counter()
                try:
                    try:
                        conn.request(
                            "POST", "/generate", body,
                            {"Content-Type": "application/json",
                             "traceparent": _tracing.child_traceparent(
                                 trace, sampled, att_sid)})
                        accepted = True
                        resp = conn.getresponse()
                        data = resp.read()
                        status = resp.status
                    except Exception as exc:  # noqa: BLE001 — sorted
                        #   into idempotent-retry vs mid-request below
                        if not accepted or isinstance(
                                exc, ConnectionRefusedError):
                            # connection-stage failure: the replica never
                            # saw the request — idempotent, re-route
                            self._mark_dead(addr, exc)
                            _TM_RETRIES.inc(reason="connect")
                            _span_attempt(t_att, att_sid, addr,
                                          "connect_error")
                            tried.add(addr)
                            last_error = exc
                            shed_only = False
                            continue
                        if isinstance(exc, (TimeoutError,
                                            socket.timeout)):
                            # accepted but slow: past generate_timeout_s
                            # the replica is NOT provably dead — surface
                            # the named 504 and keep it routable
                            _TM_ROUTED.inc(outcome="timeout")
                            _span_attempt(t_att, att_sid, addr, "timeout")
                            raise ReplicaTimeout(
                                f"replica {addr} did not answer within "
                                f"{self.generate_timeout_s}s: {exc!r} "
                                "(generation may still be running; "
                                "resubmit if safe)") from exc
                        # the request was accepted and the replica died
                        # under it: prefill/decode may have run — NOT
                        # idempotent, surface the named 502
                        self._mark_dead(addr, exc)
                        _TM_ROUTED.inc(outcome="dead")
                        _span_attempt(t_att, att_sid, addr, "died")
                        raise ReplicaDied(
                            f"replica {addr} died mid-request: {exc!r} "
                            "(generation may have started; resubmit if "
                            "safe)") from exc
                finally:
                    conn.close()
                _span_attempt(t_att, att_sid, addr, status)
                if status in (429, 503):
                    # the replica's own admission shed the request —
                    # provably no work started, re-route
                    reason = "full" if status == 429 else "draining"
                    if status == 503:
                        with self._lock:
                            row = self._replicas.get(addr)
                            if row is not None:
                                row["draining"] = True
                    _TM_RETRIES.inc(reason=reason)
                    tried.add(addr)
                    last_error = MXNetError(
                        f"replica {addr}: HTTP {status}")
                    continue
                _TM_ROUTED.inc(outcome="relayed")
                slo_ok = status < 500
                if status == 200:
                    # the replica's reply carries its TTFT — the SLO
                    # plane's latency objective reads it off the relay
                    try:
                        ttft_ms = json.loads(data).get("ttft_ms")
                        if ttft_ms is not None:
                            slo_ttft = float(ttft_ms) / 1e3
                    except (ValueError, AttributeError):
                        pass
                return status, data, addr
            if tried and not shed_only:
                _TM_ROUTED.inc(outcome="exhausted")
                raise RouterRetriesExhausted(
                    f"no replica accepted the request after trying "
                    f"{sorted(tried)} (MXTPU_ROUTER_RETRIES="
                    f"{self.retries}); last error: {last_error!r}")
            # nothing routable, or every attempt was a live 429/503
            # admission shed — the fleet is saturated/draining, not
            # broken: keep the backpressure contract (503 + Retry-After)
            _TM_ROUTED.inc(outcome="unavailable")
            raise NoReplicaAvailable(
                "every replica is draining, full, or unreachable — "
                "retry after backoff"
                + (f" (tried {sorted(tried)}: all answered 429/503)"
                   if tried else ""))
        finally:
            dur = time.perf_counter() - t0
            _TM_PROXY_SEC.observe(dur)
            # EVERY terminal outcome feeds the SLO plane — the raise
            # paths above unwind through here with slo_ok still False
            self.slo.record(slo_ok, ttft_s=slo_ttft, trace=trace)
            if traced:
                _tracing.record_span(
                    "route", "router", trace, dur, parent=ctx["parent"],
                    span=route_sid, attempts=attempts, ok=slo_ok)

    def retry_after_s(self) -> int:
        """Retry-After guidance for the router's own 503, derived from
        the cached fleet state instead of a constant: deeper aggregate
        queues push clients further out (``1 + queue_depth/slots`` over
        the routable replicas, clamped to 30 s); a fleet with NOTHING
        routable — every replica draining or dead — answers 10 s, the
        drain/restart timescale of the rolling-upgrade runbook."""
        with self._lock:
            rows = list(self._replicas.values())
        qd = slots = 0
        for r in rows:
            if not r["ok"] or r["draining"]:
                continue
            hz = r["health"] or {}
            qd += int(hz.get("queue_depth") or 0)
            slots += int(hz.get("slots") or 0)
        if slots < 1:
            return 10
        return min(1 + qd // slots, 30)

    # -------------------------------------------------------------- admin
    def _admin(self, addr, action):
        return _fleet.post_json(addr, f"/admin/{action}", {},
                                timeout=10.0)

    def drain(self, replica=None):
        """Proxy ``/admin/drain`` to one replica (or fan out to all) —
        the first step of the rolling-upgrade runbook.  Returns
        addr -> reply/error."""
        return self._fan(replica, "drain")

    def undrain(self, replica=None):
        return self._fan(replica, "undrain")

    def _fan(self, replica, action):
        addrs = [replica] if replica else list(self.replicas())
        out = {}
        for addr in addrs:
            try:
                out[addr] = self._admin(addr, action)
                with self._lock:
                    row = self._replicas.get(addr)
                    if row is not None:
                        row["draining"] = (action == "drain")
            except Exception as exc:  # noqa: BLE001 — report per replica
                out[addr] = {"error": repr(exc)}
        return out

    def wait_drained(self, addr, timeout=60.0):
        """Poll the replica's /healthz until ``drained`` (no queued or
        in-flight work left — safe to restart)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                hz = _fleet.fetch_json(addr, "/healthz", timeout=5.0)
                if hz.get("status") == "drained":
                    return True
            except OSError:
                pass
            time.sleep(0.05)
        return False

    def rolling_upgrade(self, restart_fn=None, drain_timeout=60.0):
        """Upgrade the fleet under live traffic, one replica at a time:
        drain -> wait ``drained`` -> ``restart_fn(addr)`` -> undrain.
        With the default no-op restart this is a rolling drain/undrain
        cycle (config reload); pass a function that actually restarts
        the replica process for a binary upgrade.  Returns the per-
        replica outcome list; raises if a replica never drains (the
        fleet is left with that replica draining for the operator)."""
        results = []
        for addr in sorted(self.replicas()):
            self.drain(addr)
            if not self.wait_drained(addr, timeout=drain_timeout):
                raise MXNetError(
                    f"replica {addr} did not reach 'drained' within "
                    f"{drain_timeout}s — aborting the rolling upgrade "
                    "(it keeps draining; undrain it to cancel)")
            if restart_fn is not None:
                restart_fn(addr)
            self.undrain(addr)
            self.scrape_once()
            results.append({"replica": addr, "ok": True})
        return results

    # -------------------------------------------------------------- fleet
    def fleet(self):
        """The router's ``GET /fleet``: per-replica health rows plus
        every live replica's /metrics.json merged host-labeled
        (telemetry.fleet.merge_snapshots) — the serving twin of the
        coordinator's federation endpoint."""
        rows = self.replicas()
        per_member = {}
        for addr, row in rows.items():
            if not row["ok"]:
                continue
            try:
                snap = _fleet.fetch_json(addr, "/metrics.json",
                                         timeout=5.0)
                per_member[addr] = snap.get("metrics") or {}
                row["scrape_ok"] = True
            except Exception as exc:  # noqa: BLE001 — row-level status
                row["scrape_ok"] = False
                row["scrape_error"] = repr(exc)
        return {
            "replicas": rows,
            "healthy": sum(1 for r in rows.values()
                           if r["ok"] and not r["draining"]),
            "scrape_interval_s": self.scrape_s,
            "metrics": _fleet.merge_snapshots(per_member),
        }

    # ---------------------------------------------------------- lifecycle
    def start(self):
        """Launch the background health scrape (one sweep immediately,
        so the first /generate has a routing table)."""
        if self._thread is not None:
            return self
        try:
            self.scrape_once()
        except Exception:  # noqa: BLE001 — the loop retries
            _logger.exception("router: initial scrape failed")
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.scrape_s):
                try:
                    self.scrape_once()
                except Exception:  # noqa: BLE001 — the scrape must survive
                    _logger.exception("router scrape sweep failed")

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="mxtpu-router-scrape")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def start_router(router: ReplicaRouter, port: int = 0,
                 addr: str = "127.0.0.1", registry=None):
    """Serve the router over HTTP on a daemon thread (the same shape as
    :func:`serving.server.start_server`).  Returns the HTTP server;
    ``server.shutdown()`` stops serving, ``router.stop()`` stops the
    scrape loop."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry or _tm.get_registry()
    router.start()

    class _Handler(BaseHTTPRequestHandler):
        def _reply(self, code, payload, ctype="application/json",
                   headers=()):
            body = payload if isinstance(payload, bytes) \
                else json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path in ("/", "/metrics"):
                self._reply(200, _tm.generate_text(reg).encode("utf-8"),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/metrics.json":
                self._reply(200, _tm.json_snapshot(reg))
            elif path in ("/healthz", "/replicas"):
                rows = router.replicas()
                healthy = sum(1 for r in rows.values()
                              if r["ok"] and not r["draining"])
                self._reply(200, {
                    "status": "ok" if healthy else "unavailable",
                    "role": "router",
                    "healthy": healthy,
                    "replicas": rows,
                })
            elif path == "/fleet":
                self._reply(200, router.fleet())
            elif path == "/slo":
                self._reply(200, router.slo.snapshot())
            elif path == "/spans.json":
                self._reply(200, _tracing.spans_payload())
            else:
                self._reply(404, {"error": f"no such path {path!r}"})

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            if path in ("/admin/drain", "/admin/undrain"):
                try:
                    n = int(self.headers.get("Content-Length", "0") or 0)
                    msg = json.loads(self.rfile.read(n) or b"{}")
                except ValueError as exc:
                    self._reply(400, {"error": f"malformed JSON: {exc}"})
                    return
                action = path.rsplit("/", 1)[1]
                out = (router.drain if action == "drain"
                       else router.undrain)(msg.get("replica"))
                self._reply(200, {"action": action, "replicas": out})
                return
            if path != "/generate":
                self._reply(404, {"error": f"no such path {path!r}"})
                return
            length = int(self.headers.get("Content-Length", "0") or 0)
            body = self.rfile.read(length)
            # adopt the client's traceparent or mint one HERE, so the
            # error replies below can still name the trace id
            tp = self.headers.get("traceparent")
            if _tracing.parse_traceparent(tp) is None:
                tp = _tracing.mint_traceparent()
            trace_id = _tracing.parse_traceparent(tp)["trace"]
            trace_hdr = ("X-MXTPU-Trace", trace_id)
            try:
                status, data, addr_ = router.route_generate(
                    body, traceparent=tp)
            except NoReplicaAvailable as exc:
                # Retry-After derived from fleet queue depth + drain
                # state (retry_after_s), not a constant
                self._reply(503, {"error": str(exc), "trace": trace_id},
                            headers=(("Retry-After",
                                      str(router.retry_after_s())),
                                     trace_hdr))
                return
            except (RouterRetriesExhausted, ReplicaDied) as exc:
                self._reply(502, {
                    "error": str(exc),
                    "router_error": type(exc).__name__,
                    "trace": trace_id,
                }, headers=(trace_hdr,))
                return
            except ReplicaTimeout as exc:
                self._reply(504, {
                    "error": str(exc),
                    "router_error": "ReplicaTimeout",
                    "trace": trace_id,
                }, headers=(trace_hdr,))
                return
            self._reply(status, data,
                        headers=(("X-MXTPU-Replica", addr_), trace_hdr))

        def log_message(self, *args):  # health probes are chatty
            pass

    class _Server(ThreadingHTTPServer):
        daemon_threads = True
        request_queue_size = 128

    srv = _Server((addr, port), _Handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True,
                              name="mxtpu-router-http")
    thread.start()
    return srv
