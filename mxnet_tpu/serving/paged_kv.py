"""Paged KV cache: block-table indirection + prompt-prefix reuse.

The PR-6 slot pool reserves one CONTIGUOUS ``(L, H, max_len, dh)`` cache
row per slot — a 4-token health-check request holds the same device
memory as a max_len chat, and two requests sharing a system prompt each
recompute and store identical K/V.  This module replaces the per-slot
row with vLLM-style paging: ONE shared device pool of fixed-size pages
(``MXTPU_KV_BLOCK`` tokens per page), per-slot *block tables* mapping
each slot's logical cache positions onto pool pages, gathered inside
the jitted decode programs, so

- long and short requests co-batch without padding waste (a slot holds
  exactly ``ceil(tokens/block)`` pages, not ``max_len/block``);
- identical prompt prefixes map to the SAME immutable pages: full
  prompt blocks are chain-hashed into a prefix index, admission reuses
  the longest cached chain and prefills only the tail (the shared
  system prompt is computed ONCE — ``serve_prefix_hits_total``);
- copy-on-write at the divergence point is structural: sharing is
  block-aligned and a request's first write lands at its prompt length,
  so the partially-filled divergence block is always per-fork private —
  mutating one fork can never corrupt the shared prefix (pinned by
  tests/test_serving_fleet.py).

Page allocation, refcounts, block tables and the prefix index are pure
HOST-side bookkeeping (``PagedSlots.step`` is a declared
``analysis/config.py:ENTRY_POINTS`` steady-state loop — lint proves it
never touches the device); the device work stays the serving invariant:
one jitted step over all slots per tick, one bucketed prefill per
admission, zero traces on a warm server
(``executor_compile_total{kind=decode_step_paged|decode_prefill_paged}``).

Parity: the gathered table reconstructs exactly the contiguous layout
(absolute positions, ``start=0``), the layer math is shared with
``models/decode.py``, and masked-out table entries contribute exact
zeros — paged and contiguous decode are BITWISE equal on aligned
prompts (tests pin it).
"""
from __future__ import annotations

import hashlib
import os
import time
from collections import OrderedDict
from functools import partial

import numpy as np

from .. import telemetry as _tm
from ..base import MXNetError
from ..telemetry import tracing as _tracing

__all__ = ["PagedSlots", "PoolExhausted", "kv_block", "prefix_cache_on",
           "paged_kernel_mode"]

# --- paged serving metric families (docs/telemetry.md) ----------------------
_TM_PREFIX_HITS = _tm.counter(
    "serve_prefix_hits_total",
    "prompt blocks served from the prefix cache instead of being "
    "prefilled (each hit skips one MXTPU_KV_BLOCK-token block of "
    "prefill compute)")
_TM_PAGES = _tm.gauge(
    "serve_kv_pages",
    "KV-cache page pool occupancy: total usable pages, currently free "
    "pages, and pages pinned by the prompt-prefix cache",
    labels=("state",))


class PoolExhausted(MXNetError):
    """No free KV page and nothing evictable — the pool is fully pinned
    by live requests (size the pool, or shed load upstream)."""


def kv_block() -> int:
    """``MXTPU_KV_BLOCK`` — tokens per KV page; 0/unset keeps the PR-6
    contiguous slot cache."""
    try:
        return max(int(os.environ.get("MXTPU_KV_BLOCK", "0") or 0), 0)
    except ValueError:
        return 0


def prefix_cache_on() -> bool:
    """``MXTPU_PREFIX_CACHE`` — prompt-prefix page reuse (default on
    whenever paging is on)."""
    return os.environ.get("MXTPU_PREFIX_CACHE", "1").lower() \
        not in ("0", "false", "off")


def paged_kernel_mode() -> str:
    """``MXTPU_PAGED_KERNEL`` — the step-attention lowering (ISSUE 18).

    ``auto`` (default, also ``1``): consult the autotuner — with a
    schedule cache, the tuned winner; without one, the Pallas kernel on
    a TPU whose shape qualifies and the PR-15 gather path everywhere
    else.  ``0``/``off``/``gather``: pin the gather path (bit-identical
    to PR 15).  ``pallas`` / ``interpret`` / ``pagewalk``: force one
    lowering of ``ops/paged_attention.py`` (``interpret`` is the
    CPU-parity hook; ``pagewalk`` the lax live-page walk)."""
    raw = os.environ.get("MXTPU_PAGED_KERNEL", "auto").strip().lower()
    if raw in ("", "1", "auto"):
        return "auto"
    if raw in ("0", "off", "false", "gather"):
        return "gather"
    return raw


class _PagedPrograms:
    """The jitted decode programs over the page pool.

    Pool layout ``(P, L, H, block, dh)`` — page-major so one gather by
    page id reconstructs a slot's table.  The layer math is the
    decoder's own (``_block_qkv`` + shared ``_ln``/``_fc``), run over
    the gathered table in the contiguous layout, so a paged step is
    bitwise the contiguous step whenever the table contents match.
    """

    def __init__(self, decoder, block, max_blocks, num_pages,
                 schedule=None):
        import jax

        from ..models.decode import _count_compiles

        self.dec = decoder
        self.block = int(block)
        self.max_blocks = int(max_blocks)
        self.num_pages = int(num_pages)
        # step-attention schedule (ops/paged_attention.py, picked by
        # mxnet_tpu.autotune at PagedSlots construction).  None/"gather"
        # keeps the PR-15 materialized-table math verbatim; prefill
        # always gathers (one admission-time cost, not the per-tick one)
        self.schedule = schedule if (
            schedule and schedule.get("impl") != "gather") else None
        self._step_jit = jax.jit(_count_compiles(
            self._forward_step, "decode_step_paged"))
        self._prefill_cache = {}

    def init_pool(self):
        import jax.numpy as jnp

        d = self.dec
        shape = (self.num_pages, d.L, d.H, self.block, d.dh)
        return (jnp.zeros(shape, d._cache_dtype),
                jnp.zeros(shape, d._cache_dtype))

    # ------------------------------------------------------------ gathers
    def _gather(self, pool, bt):
        """(P, L, H, blk, dh)[bt (B, M)] -> contiguous (L, B, H, S, dh)."""
        d = self.dec
        t = pool[bt]                                 # (B, M, L, H, blk, dh)
        t = t.transpose(2, 0, 3, 1, 4, 5)            # (L, B, H, M, blk, dh)
        return t.reshape(d.L, bt.shape[0], d.H,
                         self.max_blocks * self.block, d.dh)

    # ---------------------------------------------------------------- step
    def _forward_step(self, pool_k, pool_v, bt, tokens, cursor):
        """One decode position for every slot: row ``b`` writes its new
        K/V at absolute cache position ``cursor[b]`` (page
        ``bt[b, cursor//block]``, offset ``cursor%block``) and attends
        over ``[0, cursor[b]]``.  Free rows ride along with
        ``bt[b]=0``/``cursor=0`` — their writes land in the scratch
        page the allocator never hands out."""
        import jax
        import jax.numpy as jnp

        from ..models.decode import NEG_INF, _fc, _ln

        d = self.dec
        p = d.p
        B = tokens.shape[0]
        H, dh, D = d.H, d.dh, d.d_model
        S = self.max_blocks * self.block

        tok = jnp.take(p["tok_embed_weight"], tokens.astype(jnp.int32),
                       axis=0)                               # (B, D)
        pos_ids = jnp.clip(cursor, 0, d.max_len - 1)
        posv = jnp.take(p["pos_embed"][0], pos_ids, axis=0)  # (B, D)
        h = (tok + posv)[:, None]                            # (B, 1, D)
        s_idx = jnp.arange(S)
        valid = s_idx[None, :] <= cursor[:, None]            # (B, S)
        rows = jnp.arange(B)
        pages = jnp.take_along_axis(
            bt, (cursor // self.block)[:, None], axis=1)[:, 0]   # (B,)
        offs = cursor % self.block
        sched = self.schedule
        if sched is None:
            kc = self._gather(pool_k, bt)
            vc = self._gather(pool_v, bt)
        else:
            from ..ops import paged_attention as _pa
        for i in range(d.L):
            name = f"layer{i}"
            h2 = _ln(h, p[f"{name}_ln1_gamma"], p[f"{name}_ln1_beta"])
            q, k, v = d._block_qkv(i, h2)
            sh = lambda a: a.reshape(B, 1, H, dh).transpose(0, 2, 1, 3)
            qh, kh, vh = sh(q), sh(k), sh(v)                 # (B, H, 1, dh)
            if sched is None:
                kc = kc.at[i, rows, :, cursor].set(kh[:, :, 0])
                vc = vc.at[i, rows, :, cursor].set(vh[:, :, 0])
            pool_k = pool_k.at[pages, i, :, offs].set(kh[:, :, 0])
            pool_v = pool_v.at[pages, i, :, offs].set(vh[:, :, 0])
            if sched is None:
                scores = jnp.einsum("bhnd,bhsd->bhns", qh, kc[i]) \
                    / jnp.sqrt(jnp.asarray(dh, h.dtype))
                scores = jnp.where(
                    valid[:, None, None, :], scores, NEG_INF)
                att = jax.nn.softmax(scores, axis=-1)
                ctx = jnp.einsum("bhns,bhsd->bhnd", att, vc[i])
            else:
                # the kernel walks the block table over the pool the
                # writes above just updated — same values the gathered
                # table would hold, no materialization
                ctx = _pa.paged_attention(
                    qh, pool_k, pool_v, bt, cursor, i,
                    block=self.block, schedule=sched)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, 1, D)
            proj = _fc(ctx, p[f"{name}_proj_weight"],
                       p[f"{name}_proj_bias"])
            h = h + proj
            h2 = _ln(h, p[f"{name}_ln2_gamma"], p[f"{name}_ln2_beta"])
            f = _fc(h2, p[f"{name}_ffn_in_weight"],
                    p[f"{name}_ffn_in_bias"])
            f = jax.nn.gelu(f)
            f = _fc(f, p[f"{name}_ffn_out_weight"],
                    p[f"{name}_ffn_out_bias"])
            h = h + f
        h = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
        logits = _fc(h, p["lm_head_weight"], p["lm_head_bias"])
        return (pool_k, pool_v), logits[:, 0]                # (B, V)

    # ------------------------------------------------------------- prefill
    def _forward_prefill(self, pool_k, pool_v, bt_row, tokens, hist, t):
        """Tail prefill behind a (possibly reused) history: ``tokens``
        (1, T) RIGHT-padded, the ``t`` real tokens sit at absolute
        positions ``hist .. hist+t-1``.  K/V of real tokens scatter
        into their pages (and the gathered table, for intra-prefill
        attention); pad tokens target out-of-bounds indices, which the
        scatter drops.  ``hist``/``t`` ride as traced scalars, so the
        program count is one per padded bucket length."""
        import jax
        import jax.numpy as jnp

        from ..models.decode import NEG_INF, _fc, _ln

        d = self.dec
        p = d.p
        T = tokens.shape[1]
        H, dh, D = d.H, d.dh, d.d_model
        S = self.max_blocks * self.block

        j = jnp.arange(T)
        real = j < t                                         # (T,)
        qpos = hist + j                                      # absolute
        tok = jnp.take(p["tok_embed_weight"], tokens.astype(jnp.int32),
                       axis=0)                               # (1, T, D)
        posv = jnp.take(p["pos_embed"][0],
                        jnp.clip(qpos, 0, d.max_len - 1), axis=0)[None]
        h = tok + posv
        # write targets: pad tokens go out of bounds -> dropped writes
        wpos = jnp.where(real, qpos, S)                      # table scatter
        pages = jnp.where(
            real,
            bt_row[jnp.clip(qpos // self.block, 0, self.max_blocks - 1)],
            self.num_pages)                                  # pool scatter
        offs = qpos % self.block
        s_idx = jnp.arange(S)
        valid = s_idx[None, :] <= qpos[:, None]              # (T, S)
        kc = self._gather(pool_k, bt_row[None])              # (L, 1, H, S, dh)
        vc = self._gather(pool_v, bt_row[None])
        for i in range(d.L):
            name = f"layer{i}"
            h2 = _ln(h, p[f"{name}_ln1_gamma"], p[f"{name}_ln1_beta"])
            q, k, v = d._block_qkv(i, h2)
            sh = lambda a: a.reshape(1, T, H, dh).transpose(0, 2, 1, 3)
            qh, kh, vh = sh(q), sh(k), sh(v)                 # (1, H, T, dh)
            k_t = kh[0].transpose(1, 0, 2)                   # (T, H, dh)
            v_t = vh[0].transpose(1, 0, 2)
            kc = kc.at[i, 0, :, wpos].set(k_t)
            vc = vc.at[i, 0, :, wpos].set(v_t)
            pool_k = pool_k.at[pages, i, :, offs].set(k_t)
            pool_v = pool_v.at[pages, i, :, offs].set(v_t)
            scores = jnp.einsum("bhnd,bhsd->bhns", qh, kc[i]) \
                / jnp.sqrt(jnp.asarray(dh, h.dtype))
            scores = jnp.where(valid[None, None], scores, NEG_INF)
            att = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhns,bhsd->bhnd", att, vc[i])
            ctx = ctx.transpose(0, 2, 1, 3).reshape(1, T, D)
            proj = _fc(ctx, p[f"{name}_proj_weight"],
                       p[f"{name}_proj_bias"])
            h = h + proj
            h2 = _ln(h, p[f"{name}_ln2_gamma"], p[f"{name}_ln2_beta"])
            f = _fc(h2, p[f"{name}_ffn_in_weight"],
                    p[f"{name}_ffn_in_bias"])
            f = jax.nn.gelu(f)
            f = _fc(f, p[f"{name}_ffn_out_weight"],
                    p[f"{name}_ffn_out_bias"])
            h = h + f
        h = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
        logits = _fc(h, p["lm_head_weight"], p["lm_head_bias"])
        return (pool_k, pool_v), logits                      # (1, T, V)

    def prefill(self, bucket):
        if bucket not in self._prefill_cache:
            import jax

            from ..models.decode import _count_compiles

            self._prefill_cache[bucket] = jax.jit(_count_compiles(
                self._forward_prefill, "decode_prefill_paged"))
        return self._prefill_cache[bucket]


class PagedSlots:
    """Paged scheduler backend: the device pool + pure-host page
    bookkeeping (block tables, refcounts, prefix index).

    The pool holds ``num_pages`` usable pages plus page 0, a scratch
    page free rows write into (never allocated).  Default sizing —
    ``num_slots * max_len/block`` — matches the contiguous footprint,
    so prefix sharing turns straight into headroom.  Refcounts: one per
    slot whose table references the page, plus one while the prefix
    index pins it; a page drops to the free list at refcount 0.  The
    prefix index evicts LRU pages nothing else references when the
    free list runs dry; a request that still cannot get a page at
    admission fails that admission, and one starving mid-decode is
    delivered truncated (reported by :meth:`step`, finished ``ok`` by
    the scheduler like the contiguous cache-window end).
    """

    paged = True

    def __init__(self, decoder, num_slots, block=None, num_pages=None,
                 prefix_cache=None, prefill_buckets=None, kernel=None):
        if decoder.mesh is not None:
            raise MXNetError(
                "paged KV is not supported together with a tensor-"
                "parallel mesh yet (serve the paged fleet data-parallel)")
        self.decoder = decoder
        self.num_slots = int(num_slots)
        self.block = int(block if block is not None else (kv_block() or 16))
        if self.block < 1:
            raise MXNetError(f"KV block must be >= 1, got {self.block}")
        if decoder.max_len % self.block:
            raise MXNetError(
                f"MXTPU_KV_BLOCK {self.block} must divide the decoder's "
                f"max_len {decoder.max_len}")
        self.max_blocks = decoder.max_len // self.block
        self.num_pages = int(
            num_pages if num_pages is not None
            else self.num_slots * self.max_blocks)
        if self.num_pages < self.max_blocks:
            raise MXNetError(
                f"pool of {self.num_pages} pages cannot hold one "
                f"max_len request ({self.max_blocks} pages)")
        self.prefix_on = (prefix_cache_on() if prefix_cache is None
                          else bool(prefix_cache))
        self.prefill_buckets = tuple(prefill_buckets or ())
        self.kernel_mode = (paged_kernel_mode() if kernel is None
                            else str(kernel).strip().lower())
        self.schedule = self._resolve_schedule()
        self.programs = _PagedPrograms(
            decoder, self.block, self.max_blocks, self.num_pages + 1,
            schedule=self.schedule)
        self.pool = self.programs.init_pool()
        self.bt = np.zeros((self.num_slots, self.max_blocks), np.int32)
        self.cursor = np.zeros(self.num_slots, np.int32)
        self._free = list(range(self.num_pages, 0, -1))   # pop() -> page 1 last
        self._ref = np.zeros(self.num_pages + 1, np.int64)
        self._prefix = OrderedDict()      # chain hash -> page (LRU first)
        self._page_hash = {}              # page -> chain hash
        self._slot_pages = [[] for _ in range(self.num_slots)]
        # trace id of the admission currently allocating, so _alloc can
        # attribute its prefix evictions; None for step-time evictions
        self._trace_ctx = None
        # perf plane (telemetry/perf.py): one analytical cost row per
        # compiled paged program, captured at first dispatch
        self._cost_step_done = False
        self._cost_prefill_done = set()
        self._set_gauges()

    # ------------------------------------------------------------- schedule
    def _resolve_schedule(self):
        """The step-attention schedule for this pool's shape signature
        — decided ONCE, here at bind time, never per tick (the search's
        device syncs are the declared ``autotune.search.measure``
        boundary).  ``None`` means the PR-15 gather step verbatim."""
        import jax

        from .. import autotune as _autotune
        from ..ops import paged_attention as _pa

        mode = self.kernel_mode
        if mode == "gather":
            return None
        d = self.decoder
        B, M, blk = self.num_slots, self.max_blocks, self.block
        dtype = d._cache_dtype
        if mode in ("pallas", "interpret"):
            if not _pa.supports(blk, d.dh, dtype):
                return None         # shape gate even when forced
            return {"impl": "pallas", "grid": "bh", "live_only": True,
                    "interpret": mode == "interpret"}
        if mode == "pagewalk":
            return {"impl": "pagewalk", "chunk": 1}
        if mode != "auto":
            raise MXNetError(
                f"unknown MXTPU_PAGED_KERNEL mode {mode!r} (want auto, "
                "gather/0, pallas, interpret or pagewalk)")
        platform = jax.default_backend()
        default = _pa.default_schedule(platform, blk, d.dh, dtype)
        sched = _autotune.ensure(
            "paged_attention",
            _pa.keysig(B, d.H, M, blk, d.dh, dtype),
            default,
            _pa.candidate_schedules(platform, blk, d.dh, M, dtype),
            lambda c: _pa.make_bench_fn(c, B=B, H=d.H, M=M, block=blk,
                                        dh=d.dh, L=d.L, dtype=dtype))
        return None if sched.get("impl") == "gather" else dict(sched)

    # --------------------------------------------------------- bookkeeping
    def _set_gauges(self):
        _TM_PAGES.set(self.num_pages, state="total")
        _TM_PAGES.set(len(self._free), state="free")
        _TM_PAGES.set(len(self._prefix), state="prefix")

    def stats(self):
        """The ``/healthz`` ``paged`` payload."""
        return {"block": self.block,
                "pages_total": self.num_pages,
                "pages_free": len(self._free),
                "prefix_pages": len(self._prefix),
                "kernel": (self.schedule or {"impl": "gather"})["impl"]}

    def _alloc(self, n):
        """``n`` pages off the free list, evicting LRU prefix-only pages
        when it runs dry; all-or-nothing (rolls back on exhaustion)."""
        got = []
        while len(got) < n:
            if self._free:
                got.append(self._free.pop())
                continue
            evicted = None
            for hh, pg in self._prefix.items():     # LRU order
                if self._ref[pg] == 1:              # only the index holds it
                    evicted = (hh, pg)
                    break
            if evicted is None:
                self._free.extend(got)
                raise PoolExhausted(
                    f"KV page pool exhausted: {self.num_pages} pages all "
                    f"pinned by live requests (needed {n})")
            hh, pg = evicted
            del self._prefix[hh]
            del self._page_hash[pg]
            self._ref[pg] = 0
            got.append(pg)
            if _tracing.trace_on():
                _tracing.record_span(
                    "kv_evict", "replica", self._trace_ctx, 0.0, page=pg)
        for pg in got:
            self._ref[pg] = 1           # owned by the requesting slot
        return got

    def _block_hashes(self, prompt, n_blocks):
        """Chain hashes of the prompt's full blocks: ``d_i = H(d_{i-1}
        || tokens_i)`` — a block's hash commits to its whole prefix, so
        one dict hit per block reconstructs the longest shared chain."""
        prev = b"mxtpu-prefix"
        out = []
        for i in range(n_blocks):
            prev = hashlib.blake2b(
                prev + prompt[i * self.block:(i + 1) * self.block]
                .tobytes(), digest_size=16).digest()
            out.append(prev)
        return out

    @property
    def max_prompt(self):
        return self.decoder.max_len

    # ------------------------------------------------------------ admission
    def admit(self, slot, prompt, trace=None):
        """Prefix lookup + page allocation + ONE bucketed tail prefill
        writing straight into the pool; returns the next-token logits
        row of the last prompt token.  ``trace``: the admitting
        request's trace id — kv_admit/kv_prefix_hit spans land under
        it, and prefix pages evicted to make room are attributed to it
        (ISSUE 16)."""
        import jax.numpy as jnp

        from ..models.decode import _snap

        t_kv0 = time.perf_counter()
        prompt = np.asarray(prompt, np.int64)
        p_len = int(prompt.size)
        blk = self.block
        n_full = p_len // blk
        hashes = self._block_hashes(prompt, n_full) if self.prefix_on \
            else []
        shared = []
        # reuse the longest cached chain, capped so >=1 tail token is
        # always prefilled (its logits seed the first sampled token) and
        # the cursor page stays fork-private
        for i in range((p_len - 1) // blk):
            pg = self._prefix.get(hashes[i]) if i < len(hashes) else None
            if pg is None:
                break
            shared.append(pg)
            self._prefix.move_to_end(hashes[i])
        n_shared = len(shared)
        hist = n_shared * blk
        tail = prompt[hist:]
        t = int(tail.size)
        # pin the matched chain BEFORE allocating: _alloc evicts ref==1
        # prefix pages, which would otherwise include this request's own
        # shared chain under pool pressure — the evicted page would come
        # back as an owned tail page and the prefill would overwrite the
        # shared prefix
        for pg in shared:
            self._ref[pg] += 1
        self._trace_ctx = trace
        try:
            owned = self._alloc((p_len + blk - 1) // blk - n_shared)
        except PoolExhausted:
            for pg in shared:
                self._ref[pg] -= 1
            raise
        finally:
            self._trace_ctx = None
        row = shared + owned
        self.bt[slot, :len(row)] = row
        self.bt[slot, len(row):] = 0
        self._slot_pages[slot] = list(row)
        if n_shared:
            _TM_PREFIX_HITS.inc(n_shared)
        bucket = next(b for b in self.prefill_buckets if b >= t)
        padded = np.zeros((1, bucket), np.int64)
        padded[0, :t] = tail
        # _snap: self.bt is mutated in place by later admits/steps while
        # this dispatch may still be executing — never alias it
        (pk, pv), logits = self.programs.prefill(bucket)(
            self.pool[0], self.pool[1], _snap(self.bt[slot]),
            jnp.asarray(padded), jnp.int32(hist), jnp.int32(t))
        if bucket not in self._cost_prefill_done and _tm.perf.enabled():
            self._cost_prefill_done.add(bucket)
            _tm.perf.attach_cost_analysis(
                f"decode_prefill_paged[b{bucket}]",
                self.programs.prefill(bucket),
                pk, pv, _snap(self.bt[slot]), jnp.asarray(padded),
                jnp.int32(hist), jnp.int32(t))
        self.pool = (pk, pv)
        self.cursor[slot] = p_len
        # promote this prompt's full blocks: they are never written
        # again (writes happen at cursor >= p_len), so they are safe to
        # share with every later identical prefix
        if self.prefix_on:
            for i in range(n_full):
                if hashes[i] not in self._prefix:
                    pg = row[i]
                    self._prefix[hashes[i]] = pg
                    self._page_hash[pg] = hashes[i]
                    self._ref[pg] += 1
        self._set_gauges()
        if trace is not None and _tracing.trace_on():
            if n_shared:
                _tracing.record_span(
                    "kv_prefix_hit", "replica", trace, 0.0,
                    blocks=n_shared, tokens=hist)
            _tracing.record_span(
                "kv_admit", "replica", trace,
                time.perf_counter() - t_kv0, slot=slot,
                pages_shared=n_shared, pages_owned=len(owned),
                bucket=bucket)
        return logits[0, t - 1]

    # ----------------------------------------------------------------- tick
    def step(self, tokens, occupied):
        """One jitted step over the pool (the paged allocator tick —
        declared in analysis/config.py:ENTRY_POINTS).  Rows crossing a
        block boundary get their next page here; a row the pool cannot
        feed is reported in ``starved`` for the scheduler to deliver
        truncated (its garbage write lands in the scratch page)."""
        from ..models.decode import _snap

        starved = []
        for b in np.flatnonzero(occupied):
            b = int(b)
            c = int(self.cursor[b])
            if c >= self.decoder.max_len:
                raise MXNetError(
                    f"slot cursor at max_len {self.decoder.max_len}: "
                    "finish or evict the request before ticking it")
            idx = c // self.block
            if c % self.block == 0 and len(self._slot_pages[b]) <= idx:
                try:
                    pg = self._alloc(1)[0]
                except PoolExhausted:
                    starved.append(b)
                    continue
                self.bt[b, idx] = pg
                self._slot_pages[b].append(pg)
        # _snap: bt/cursor are mutated in place right below and on the
        # next tick — aliasing them into the async dispatch races
        (pk, pv), logits = self.programs._step_jit(
            self.pool[0], self.pool[1], _snap(self.bt),
            _snap(tokens), _snap(self.cursor))
        if not self._cost_step_done and _tm.perf.enabled():
            self._cost_step_done = True
            _tm.perf.attach_cost_analysis(
                "decode_step_paged", self.programs._step_jit,
                pk, pv, _snap(self.bt), _snap(tokens),
                _snap(self.cursor))
        self.pool = (pk, pv)
        adv = occupied.copy()
        adv[starved] = False
        self.cursor[adv] += 1
        if starved:
            self._set_gauges()
        return logits, starved

    def exhausted(self, slot):
        return self.cursor[slot] >= self.decoder.max_len

    def release(self, slot):
        for pg in self._slot_pages[slot]:
            self._ref[pg] -= 1
            if self._ref[pg] == 0:
                self._free.append(pg)
        self._slot_pages[slot] = []
        self.bt[slot] = 0
        self.cursor[slot] = 0
        self._set_gauges()
