"""Operator-level profiler emitting chrome://tracing JSON.

Parity: src/engine/profiler.{h,cc} (Profiler/OprExecStat/DevStat,
DumpProfile/EmitEvent emit chrome-trace events) + python/mxnet/profiler.py
(profiler_set_config/profiler_set_state/dump_profile) + env autostart
MXNET_PROFILER_AUTOSTART / MXNET_PROFILER_MODE (docs/how_to/env_var.md:64-67).

TPU-native twist: alongside the host-side per-op trace we can start a
real XLA/xprof device trace (jax.profiler.start_trace) so kernel-level
timelines land next to the op-level one — the unified view SURVEY.md §5.1
calls for.  Host-side timing wraps the *dispatch + optional device sync*:
under mode='all' every timed op is blocked on (accurate, slow); under
mode='symbolic' only executor-level spans are recorded (cheap).
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

from .base import get_env

_lock = threading.Lock()
_state = {
    "mode": os.environ.get("MXNET_PROFILER_MODE", "symbolic"),
    "filename": "profile.json",
    "running": bool(get_env("MXNET_PROFILER_AUTOSTART", 0, int)),
    "xla_trace_dir": None,
    "xla_tracing": False,
}
_events: list = []
_t0 = time.monotonic()


def _now_us() -> float:
    return (time.monotonic() - _t0) * 1e6


def now_us() -> float:
    """Microseconds on the profiler's timeline (shared timebase for
    telemetry spans, so host metrics and op traces line up)."""
    return _now_us()


def profiler_set_config(mode="symbolic", filename="profile.json",
                        xla_trace_dir=None):
    """Parity: MXSetProfilerConfig (src/c_api/c_api.cc).  mode is
    'symbolic' (executor spans only) or 'all' (imperative ops too, each
    synced for accurate timing).  xla_trace_dir additionally captures an
    xprof/XLA device trace."""
    if mode not in ("symbolic", "all"):
        raise ValueError("mode must be 'symbolic' or 'all'")
    with _lock:
        _state["mode"] = mode
        _state["filename"] = filename
        _state["xla_trace_dir"] = xla_trace_dir


def profiler_set_state(state="stop"):
    """Parity: MXSetProfilerState; 'run' or 'stop'."""
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    with _lock:
        _state["running"] = state == "run"
        if _state["xla_trace_dir"]:
            import jax

            if _state["running"] and not _state["xla_tracing"]:
                jax.profiler.start_trace(_state["xla_trace_dir"])
                _state["xla_tracing"] = True
            elif not _state["running"] and _state["xla_tracing"]:
                jax.profiler.stop_trace()
                _state["xla_tracing"] = False


def is_running() -> bool:
    return _state["running"]


def mode() -> str:
    return _state["mode"]


def record(name: str, device: str, start_us: float, end_us: float,
           category: str = "operator"):
    """Append one complete ('X') chrome-trace event (parity: OprExecStat +
    EmitEvent, src/engine/profiler.h:90-110)."""
    with _lock:
        _events.append({
            "name": name,
            "cat": category,
            "ph": "X",
            "ts": start_us,
            "dur": max(end_us - start_us, 0.0),
            "pid": device,
            "tid": threading.get_ident() & 0xFFFF,
        })


@contextmanager
def span(name: str, device: str = "cpu/0", category: str = "operator",
         sync=None):
    """Time a region if the profiler is running.  ``sync`` is an optional
    zero-arg callable run before closing the span (e.g. block_until_ready)
    so async dispatch doesn't under-report."""
    if not _state["running"]:
        yield
        return
    start = _now_us()
    try:
        yield
    finally:
        if sync is not None:
            try:
                sync()
            except Exception:
                pass
        record(name, device, start, _now_us(), category)


def dump_profile(filename=None):
    """Parity: MXDumpProfile — write accumulated events as
    chrome://tracing JSON and clear the buffer."""
    fname = filename or _state["filename"]
    with _lock:
        payload = {"traceEvents": list(_events), "displayTimeUnit": "ms"}
        _events.clear()
    with open(fname, "w") as f:
        json.dump(payload, f)
    return fname


def clear():
    with _lock:
        _events.clear()
