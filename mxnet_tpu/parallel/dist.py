"""Multi-host process runtime over jax.distributed — the collective side
of ``dist_sync`` plus the elastic control-plane primitives.

Parity: the ps-lite ``Postoffice`` role (ranks, barriers, dead-node
surface — include/mxnet/kvstore.h:158-242) for TPU pods, where process
wiring is jax.distributed + ICI/DCN collectives instead of a ZMQ
scheduler.  The host-TCP parameter server lives in kvstore_server.py;
this module is the collective-native side:

- :func:`init_from_env` wires jax.distributed from launcher env vars
  (validated — a bad rank used to surface as an opaque jax hang), and
  enables gloo CPU collectives so the multi-process-on-CPU test rig
  runs the SAME cross-process XLA programs a pod runs over DCN.
- :func:`barrier` is the cross-host rendezvous with a **watchdog**:
  ``MXTPU_DIST_BARRIER_TIMEOUT_S`` bounds the wait, and expiry raises
  :class:`HostLostError` naming host/rank/generation + the
  flight-record dump instead of hanging the survivors forever inside
  ``sync_global_devices``.
- **Generations** (:func:`generation`): the elastic runtime's epoch
  number.  Every process of one training incarnation shares a
  generation; membership changes (host death, rejoin) publish the next
  one through the coordinator (parallel/coordinator.py) and every
  member re-enters through checkpoint-resume on the new mesh.

Why restart-per-generation instead of shrinking in place: a peer death
wedges survivors inside the blocked collective, and the jax runtime
hard-aborts the process on coordination-service heartbeat timeout
(~100s) — there is no supported in-process world-shrink.  The elastic
contract is therefore: detect FAST (coordinator leases, seconds),
checkpoint at the boundary (or fall back to the PR-11 periodic async
checkpoint when wedged mid-collective), exit with
:data:`EXIT_HOST_LOST`, and let the launcher (tools/launch.py
``--elastic``) relaunch the surviving membership at the next generation
— `Module.fit`/`FusedTrainer.fit` re-bind on the new mesh shape via the
checkpoint re-shard contract (``sync_shard_state``).
"""
from __future__ import annotations

import logging
import os
import threading

from ..base import MXNetError
from .. import telemetry as _tm

_logger = logging.getLogger("mxnet_tpu.parallel.dist")

#: Process exit code for "this worker left its generation on purpose"
#: (host lost / membership changed): the elastic launcher relaunches the
#: next generation instead of counting it as a crash.
EXIT_HOST_LOST = 43

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_ALLREDUCE_BYTES = _tm.counter(
    "dist_allreduce_bytes_total",
    "logical gradient bytes entering the cross-host in-trace all-reduce "
    "of the collective dist_sync path (dispatch-side accounting; the "
    "reduction itself runs inside the compiled step)")
_TM_BARRIER_SEC = _tm.histogram(
    "dist_barrier_seconds",
    "cross-host barrier wall time (sync_global_devices under the "
    "MXTPU_DIST_BARRIER_TIMEOUT_S watchdog)")


class HostLostError(MXNetError):
    """A cross-host blocking site timed out or the cluster membership
    changed under us: a peer host is gone (or joining) and this
    process must leave its generation.

    Attributes name everything the operator (and the elastic launcher)
    needs: ``host`` (the peer believed dead, or ``"?"``), ``rank`` /
    ``generation`` of THIS process, ``site`` (barrier / collective /
    coordinator), and ``dump`` (flight-record path, when
    ``MXTPU_FLIGHT_RECORD`` names one).  Exit with
    :data:`EXIT_HOST_LOST` after catching it so the elastic launcher
    relaunches the next generation.
    """

    def __init__(self, site, host="?", rank=None, generation=None,
                 dump=None, detail=""):
        self.site = site
        self.host = host
        self.rank = _rank_or_env() if rank is None else int(rank)
        self.generation = generation if generation is not None \
            else _generation_env()
        self.dump = dump
        msg = (f"host lost at site {site!r}: host={host} "
               f"rank={self.rank} generation={self.generation}")
        if detail:
            msg += f" ({detail})"
        if dump:
            msg += f" (flight record: {dump})"
        super().__init__(msg)


class GenerationChanged(HostLostError):
    """The coordinator published a new cluster generation (a host died
    or a new one joined) and this process checkpointed at the boundary:
    leave cleanly with :data:`EXIT_HOST_LOST` and rejoin the next
    generation through resume."""


def _generation_env() -> int:
    try:
        return int(os.environ.get("MXTPU_DIST_GENERATION", "0") or 0)
    except ValueError:
        return 0


def generation() -> int:
    """The cluster generation this process was launched into (set by
    the elastic launcher; 0 for non-elastic runs)."""
    return _generation_env()


def _rank_or_env() -> int:
    """This process's rank WITHOUT initializing jax backends (env view;
    error paths must be safe before/without jax.distributed)."""
    try:
        return int(os.environ.get("MXTPU_RANK",
                                  os.environ.get("DMLC_RANK", "0")) or 0)
    except ValueError:
        return 0


def barrier_timeout_s() -> float:
    """MXTPU_DIST_BARRIER_TIMEOUT_S — watchdog bound on every
    cross-host rendezvous (default 600s; must stay well under the jax
    coordination-service abort at ~100s only when tuned down — see
    docs/multihost.md).  <=0 disables the watchdog."""
    try:
        return float(os.environ.get("MXTPU_DIST_BARRIER_TIMEOUT_S", "600"))
    except ValueError:
        return 600.0


def _validate_coordinator(coord: str):
    """A well-formed ``host:port``.  jax.distributed turns a malformed
    address into an opaque hang/abort — name the offending value."""
    host, sep, port = str(coord).rpartition(":")
    ok = bool(sep) and bool(host)
    if ok:
        try:
            ok = 0 < int(port) < 65536
        except ValueError:
            ok = False
    if not ok:
        raise MXNetError(
            f"MXTPU_COORDINATOR={coord!r}: expected 'host:port' with a "
            "port in 1..65535 (e.g. '10.0.0.1:8476')")


def _enable_cpu_collectives():
    """Cross-process collectives on the CPU backend need the gloo
    implementation — without it every multi-process CPU program fails
    with 'Multiprocess computations aren't implemented on the CPU
    backend'.  Harmless on accelerator backends (config only affects
    CPU); skipped when the installed jax predates the option.

    CPU dispatch also goes synchronous: gloo context creation races
    when concurrently-executing programs bring up communicators at the
    same time (observed as a hard ``gloo::EnforceNotMet`` preamble-
    mismatch abort on jaxlib 0.4.36), and serializing CPU execution
    removes the concurrency.  Accelerator programs never run on the
    CPU backend, so pods are unaffected; the multi-process CPU rig is
    a test/bench vehicle where throughput is irrelevant."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — older jax: accelerator-only dist
        _logger.warning("jax_cpu_collectives_implementation unavailable; "
                        "multi-process CPU collectives will not work")
        return
    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    except Exception:  # noqa: BLE001 — option renamed/absent: best effort
        pass


def init_from_env():
    """Initialize jax.distributed from standard launcher env vars
    (parity: InitPSEnv, include/mxnet/kvstore.h:158-208).  No-op if
    single-process or already initialized.

    Validates the env contract FIRST: ``MXTPU_RANK`` must be an integer
    in ``[0, MXTPU_NUM_WORKERS)`` and ``MXTPU_COORDINATOR`` a
    well-formed ``host:port`` — a bad rank used to surface as an opaque
    jax.distributed hang."""
    import jax

    # NB: do not probe jax.process_count() here — it initializes the XLA
    # backends, after which jax.distributed.initialize() would fail.
    # Check the distributed client state directly instead.
    try:
        from jax._src import distributed as _jd

        if _jd.global_state.client is not None:
            return
    except Exception:
        pass
    coord = os.environ.get("MXTPU_COORDINATOR",
                           os.environ.get("JAX_COORDINATOR_ADDRESS"))
    try:
        nproc = int(os.environ.get("MXTPU_NUM_WORKERS", "1"))
        rank = int(os.environ.get("MXTPU_RANK", "0"))
    except ValueError as exc:
        raise MXNetError(
            f"MXTPU_RANK={os.environ.get('MXTPU_RANK')!r} / "
            f"MXTPU_NUM_WORKERS={os.environ.get('MXTPU_NUM_WORKERS')!r}: "
            "both must be integers") from exc
    if not coord or nproc <= 1:
        return
    if not 0 <= rank < nproc:
        raise MXNetError(
            f"MXTPU_RANK={rank} out of range for "
            f"MXTPU_NUM_WORKERS={nproc} (need 0 <= rank < num_workers); "
            "check the launcher env")
    _validate_coordinator(coord)
    _enable_cpu_collectives()
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=rank)


def rank() -> int:
    import jax

    return jax.process_index()


def num_workers() -> int:
    import jax

    return jax.process_count()


def log_prefix() -> str:
    """``"[rank/size@g<generation>] "`` when jax.distributed spans >1
    process, else ``""`` — the identity prefix Speedometer and the
    telemetry LoggingReporter stamp on their lines so interleaved logs
    from the elastic launcher stay attributable.  Reads the distributed
    client state directly (never initializes a backend)."""
    ident = _log_identity()
    return "[%d/%d@g%d] " % ident if ident else ""


def _log_identity():
    """(rank, size, generation) of a live multi-process world, or None
    (single-process / uninitialized).  Split out so tests can fake a
    world without bringing up jax.distributed."""
    try:
        from jax._src import distributed as _jd

        st = _jd.global_state
        if st.client is None or not st.num_processes \
                or int(st.num_processes) <= 1:
            return None
        return (int(st.process_id), int(st.num_processes), generation())
    except Exception:  # noqa: BLE001 — logging must never require dist
        return None


def is_multi_host() -> bool:
    """True when jax.distributed spans >1 process (without initializing
    it: env says multi-worker, or a live backend says so)."""
    try:
        from jax._src import distributed as _jd

        if _jd.global_state.client is not None:
            import jax

            return jax.process_count() > 1
    except Exception:
        pass
    try:
        return int(os.environ.get("MXTPU_NUM_WORKERS", "1")) > 1 and bool(
            os.environ.get("MXTPU_COORDINATOR",
                           os.environ.get("JAX_COORDINATOR_ADDRESS")))
    except ValueError:
        return False


def _sync_global_devices(name):
    """Indirection point for the barrier collective (tests substitute a
    slow double to exercise the watchdog without a real second host)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def barrier(name: str = "mxtpu_barrier", timeout: float = None):
    """Cross-host sync (parity: KVStore::Barrier → ps::Postoffice
    barrier).  Rides a tiny DCN all-reduce — under a watchdog.

    A dead peer parks ``sync_global_devices`` forever (and the jax
    runtime only aborts the process minutes later): the collective runs
    on a helper thread and the caller waits at most ``timeout``
    (default ``MXTPU_DIST_BARRIER_TIMEOUT_S``), then raises
    :class:`HostLostError` carrying rank/generation + the flight-record
    dump.  The helper thread stays parked in the dead collective — the
    process is expected to exit (:data:`EXIT_HOST_LOST`) and be
    relaunched into the next generation, which is the only recovery
    jax.distributed supports.
    """
    import time

    import jax

    from .. import faults as _faults

    if jax.process_count() <= 1:
        return
    if _faults.maybe_fail("dist_barrier"):
        # injected drop = simulated dead-peer timeout, without the wait
        raise HostLostError("barrier", dump=_tm.health.auto_dump("fault"),
                            detail=f"injected dist_barrier drop ({name})")
    timeout = barrier_timeout_s() if timeout is None else float(timeout)
    t0 = time.perf_counter()
    if timeout <= 0:
        _sync_global_devices(name)
    else:
        done = threading.Event()
        err = []

        def _run():
            try:
                _sync_global_devices(name)
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                err.append(exc)
            finally:
                done.set()

        t = threading.Thread(target=_run, daemon=True,
                             name=f"mxtpu-barrier[{name}]")
        t.start()
        if not done.wait(timeout):
            raise HostLostError(
                "barrier", dump=_tm.health.auto_dump("fault"),
                detail=f"barrier {name!r} timed out after {timeout:g}s "
                       "(a peer host stopped participating)")
        if err:
            raise err[0]
    if _tm.enabled():
        _TM_BARRIER_SEC.observe(time.perf_counter() - t0)


def elastic_main(fn):
    """Run one generation of an elastic worker: call ``fn()`` and
    convert a :class:`HostLostError` (membership change, dead peer,
    lost coordinator) into :data:`EXIT_HOST_LOST` so the elastic
    launcher relaunches the next generation.

    The exit is ``os._exit`` ON PURPOSE: after a peer death the jax
    atexit shutdown parks on the distributed shutdown barrier and the
    coordination client hard-aborts the process (exit -6) — the state
    worth saving is already in the boundary/periodic checkpoint, so the
    clean move is to skip interpreter teardown entirely."""
    import sys

    def _leave(exc):
        _logger.warning("leaving generation: %s", exc)
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(EXIT_HOST_LOST)

    try:
        return fn()
    except HostLostError as exc:
        _leave(exc)
    except BaseException as exc:
        # a dead peer usually surfaces FIRST as a wedged collective
        # blowing a runtime error (gloo context timeout) — before the
        # loop reaches its next coordinator poll.  If the membership
        # moved (or the coordinator is gone), this IS a host-lost exit,
        # not a crash: the launcher should relaunch, resuming from the
        # last complete checkpoint.
        try:
            from . import coordinator as _coord

            client = _coord._default_client
        except Exception:  # noqa: BLE001 — conversion is best-effort
            client = None
        if client is not None and (client.changed() or client._lost):
            _tm.health.auto_dump("fault")
            _leave(HostLostError(
                "collective", rank=client.rank,
                generation=client.generation,
                detail=f"runtime error after membership change: {exc!r}"))
        raise


def count_allreduce_bytes(nbytes: int):
    """Dispatch-side accounting for the collective dist_sync gradient
    payload (the all-reduce itself is inside the compiled step)."""
    if _tm.enabled() and nbytes > 0:
        _TM_ALLREDUCE_BYTES.inc(int(nbytes))
