"""Multi-host process group helpers over jax.distributed.

Parity: the ps-lite ``Postoffice`` role (ranks, barriers, dead-node
surface — include/mxnet/kvstore.h:158-242) for TPU pods, where process
wiring is jax.distributed + ICI/DCN collectives instead of a ZMQ
scheduler.  The host-TCP parameter server lives in kvstore_server.py;
this module is the collective-native side.
"""
from __future__ import annotations

import os


def init_from_env():
    """Initialize jax.distributed from standard launcher env vars
    (parity: InitPSEnv, include/mxnet/kvstore.h:158-208).  No-op if
    single-process or already initialized."""
    import jax

    # NB: do not probe jax.process_count() here — it initializes the XLA
    # backends, after which jax.distributed.initialize() would fail.
    # Check the distributed client state directly instead.
    try:
        from jax._src import distributed as _jd

        if _jd.global_state.client is not None:
            return
    except Exception:
        pass
    coord = os.environ.get("MXTPU_COORDINATOR",
                           os.environ.get("JAX_COORDINATOR_ADDRESS"))
    nproc = int(os.environ.get("MXTPU_NUM_WORKERS", "1"))
    rank = int(os.environ.get("MXTPU_RANK", "0"))
    if coord and nproc > 1:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=rank)


def rank() -> int:
    import jax

    return jax.process_index()


def num_workers() -> int:
    import jax

    return jax.process_count()


def barrier(name: str = "mxtpu_barrier"):
    """Cross-host sync (parity: KVStore::Barrier → ps::Postoffice
    barrier).  Rides a tiny DCN all-reduce."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
