"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

The reference approximates pipelining with ctx_group placement + the
dependency engine's opportunistic overlap (docs/how_to/
model_parallel_lstm.md); there is no scheduled-microbatch pipeline.
TPU-native design goes further: stages live on a 'pipe' mesh axis, and
one `shard_map`-wrapped `lax.scan` drives the classic GPipe schedule —
each tick every device runs its stage on the activation `ppermute`d from
the previous stage, so the whole pipeline (fill, steady state, drain) is
ONE XLA program.  Backward falls out of jax autodiff: the transpose of
ppermute is the reverse rotation, giving the mirror-image backward
schedule for free.

Schedule & memory profile:
- bubble: (S-1)/(S-1+M) of ticks are fill/drain for S stages and M
  microbatches (`bubble_fraction`); amortize with M >> S.
- activation memory: the autodiff of the scan saves each tick's stage
  activations, i.e. the GPipe profile (O(M) per stage).  1F1B's memory
  advantage (O(S) in-flight microbatches) is obtained here the XLA way:
  pass ``remat=True`` to checkpoint each stage invocation so backward
  recomputes stage activations tick by tick — the scan carry is then the
  only live activation, at ~1/3 extra stage FLOPs (same trade the
  reference exposes as MXNET_BACKWARD_DO_MIRROR, env_var.md:55-57).
- input/output replication: the microbatched input is replicated to all
  stages and outputs are psum-shared (losses are computed replicated) —
  per-device feed memory is O(batch), same order as data-parallel
  training; the per-stage *weights and activations* are what pipelining
  shards.  For feeds too big to replicate, stream microbatches from host
  with a prefetching iterator instead of staging the whole batch.

Real models: stages don't need to be single layers.  The usual layout is
embed/head OUTSIDE the pipeline (computed with plain GSPMD sharding) and
the repeated trunk inside, `blocks_per_stage` blocks per device via
`stacked_blocks_stage` (tests/test_pipeline_moe.py pipelines a 4-block
transformer LM; examples/model-parallel-lstm/lstm_pipeline.py pipelines
the reference's model-parallel LSTM-PTB workload with one LSTM layer per
stage).

Shapes:
- stage parameters are stacked on a leading stage axis and sharded over
  'pipe' (each device holds its stage's slice),
- the microbatched input is [n_micro, micro_batch, ...],
- every stage maps the activation shape to itself (equal-width trunk;
  width changes belong outside the pipelined region).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .mesh import shard_map


def stack_stage_params(per_stage_params):
    """[{name: array}, ...] -> {name: array stacked on axis 0} (all stages
    must share parameter structure — the usual 'repeated block' layout)."""
    names = per_stage_params[0].keys()
    return {n: jnp.stack([p[n] for p in per_stage_params]) for n in names}


def shard_stacked(mesh: Mesh, stacked, axis_name: str = "pipe"):
    """Place each stage's parameter slice on its pipeline device."""
    return {
        n: jax.device_put(
            v, NamedSharding(mesh, P(axis_name, *([None] * (v.ndim - 1)))))
        for n, v in stacked.items()
    }


def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """Idle fraction of the GPipe forward scan (`pipeline_apply`):
    (S-1)/(S-1+M) — each stage does M useful ticks out of M+S-1."""
    return (n_stages - 1) / (n_stages - 1 + n_micro)


def bubble_fraction_1f1b(n_stages: int, n_micro: int) -> float:
    """Idle fraction of the LOCKSTEP 1F1B train step
    (`make_pipeline_train_step`): (2S-1)/(M+2S-1).

    Each of the M+2S-1 ticks costs one forward plus one backward on
    every device (masked slots still execute), and a stage fills M of
    its fwd slots and M of its bwd slots — so the fill/drain is ~2x the
    classic asynchronous 1F1B's (S-1)/(M+S-1).  That is the price of
    running the whole schedule as one SPMD scan; amortize with M >> S,
    which the O(S) activation stash makes affordable."""
    return (2 * n_stages - 1) / (n_micro + 2 * n_stages - 1)


def stacked_blocks_stage(block_fn):
    """Build a stage_fn running `blocks_per_stage` identical blocks.

    block_fn(block_params, x) -> y.  The per-stage parameter slice must
    carry a leading block axis on every leaf ({name: [B, ...]}); the
    blocks run sequentially via lax.scan.  With stack_stage_params the
    full tree is {name: [n_stages, B, ...]} — L = n_stages*B total
    blocks, the standard "repeated trunk" pipeline layout.
    """

    def stage_fn(params, x, stage):
        def body(h, blk):
            return block_fn(blk, h), None

        y, _ = jax.lax.scan(body, x, params)
        return y

    return stage_fn


def pipeline_apply(stage_fn, stacked_params, micro_inputs, mesh: Mesh,
                   axis_name: str = "pipe", remat: bool = False):
    """Run the GPipe schedule; returns [n_micro, ...] last-stage outputs.

    stage_fn(params_slice, x, stage_index) -> y; every stage must map the
    same activation shape to itself (classic equal-width pipeline).
    stage_index arrives as a traced scalar — use jnp.where/lax.cond on it
    for stage-dependent behavior.  remat=True recomputes stage
    activations in backward (1F1B's memory profile; module docstring).
    """
    n_stages = mesh.shape[axis_name]
    n_micro = micro_inputs.shape[0]
    ticks = n_micro + n_stages - 1
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn

    param_specs = {n: P(axis_name, *([None] * (v.ndim - 1)))
                   for n, v in stacked_params.items()}

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, P()),
             out_specs=P(),
             check_rep=False)
    def run(params, xs):
        # params: {name: [1, ...]} my stage's slice; xs: [n_micro, mb, ...]
        my = {n: v[0] for n, v in params.items()}
        stage = jax.lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        act_shape = xs.shape[1:]

        def tick(carry, t):
            held = carry  # activation this device just produced
            # rotate activations one stage forward; stage 0's incoming slot
            # is then overwritten by the next microbatch (or zeros while
            # draining)
            incoming = jax.lax.ppermute(held, axis_name, fwd_perm)
            feed = jnp.where(
                t < n_micro,
                jax.lax.dynamic_index_in_dim(
                    xs, jnp.minimum(t, n_micro - 1), keepdims=False),
                jnp.zeros(act_shape, xs.dtype))
            x_in = jnp.where(stage == 0, feed, incoming)
            y = fn(my, x_in, stage)
            # only the last stage's finished ticks are real outputs
            out = jnp.where(stage == n_stages - 1, y,
                            jnp.zeros_like(y))
            return y, out

        _, outs = jax.lax.scan(tick, jnp.zeros(act_shape, xs.dtype),
                               jnp.arange(ticks))
        # tick t on the last stage finishes microbatch t-(n_stages-1);
        # gather those and share them with every stage (losses are
        # computed replicated)
        outs = outs[n_stages - 1:]
        return jax.lax.psum(outs, axis_name)

    return run(stacked_params, micro_inputs)


def microbatch(x, n_micro):
    """[batch, ...] -> [n_micro, batch/n_micro, ...]."""
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by {n_micro}")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


# ===========================================================================
# Heterogeneous pipeline: per-stage parameter trees, shape-changing stage
# boundaries, and a 1F1B training schedule.
#
# The stacked-array pipeline above requires every stage to share one
# parameter structure and one activation shape — fine for a repeated
# trunk, wrong for a real model whose first stage embeds tokens and whose
# last stage projects to the vocabulary.  This section removes both
# restrictions:
#
# * per-stage pytrees: stages hand in arbitrary (and different) parameter
#   trees.  Internally the UNION of all stages' leaves is stacked on a
#   leading stage axis and sharded over the pipe axis — each device
#   materializes real values for its own stage's leaves and zeros for the
#   others (zeros cost memory: keep per-stage-exclusive leaves small or
#   shard them further, e.g. vocab-shard a large embedding over 'pipe'
#   and all_gather it inside the stage).  Stage dispatch is a
#   `lax.switch` on the device's pipe index — SPMD-legal because the
#   branches contain no collectives.
#
# * shape-changing boundaries: inter-stage activations are flattened per
#   sample and padded to the widest boundary, so stage i may map
#   [mb, T, D] -> [mb, T, 4D] (or an LSTM pipeline may narrow its hidden
#   width per layer).  `ppermute` moves one uniform [mb, F] buffer; each
#   stage statically slices/reshapes its true input and pads its output.
#
# * 1F1B schedule (`make_pipeline_train_step`): one fused XLA program
#   scans T = M + 2S - 1 ticks; at tick t, stage s runs the forward of
#   microbatch t-s and the backward of microbatch t+s-(2S-1) (each when
#   in range).  Forward activations rotate s->s+1 and backward cotangents
#   rotate s->s-1 every tick.  Per-stage activation memory is a
#   2S+1-deep stash of boundary INPUTS (backward recomputes the stage,
#   remat-style, via jax.vjp at the bwd tick) — O(S) in-flight
#   microbatches versus the O(M) residuals autodiff keeps for the GPipe
#   scan, at the standard one-extra-forward remat cost.  Idle fraction
#   is (2S-1)/(M+2S-1) (`bubble_fraction_1f1b` — the lockstep scan pays
#   ~2x the classic 1F1B fill/drain; amortize with M >> S); `tools/
#   pipeline_memory.py` prints the measured memory table.
# ===========================================================================


def _tree_paths(tree):
    """Pytree -> (ordered path-key list, {path: leaf}, treedef)."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, treedef = tree_flatten_with_path(tree)
    keys = [keystr(p) for p, _ in leaves]
    return keys, dict(zip(keys, (v for _, v in leaves))), treedef


class UnionMeta:
    """Bookkeeping for per-stage trees embedded in one stacked union."""

    def __init__(self, per_stage_params):
        self.n_stages = len(per_stage_params)
        self.stage_keys = []   # per stage: ordered leaf path keys
        self.stage_defs = []   # per stage: treedef
        self.union = {}        # path -> (shape, dtype)
        for tree in per_stage_params:
            keys, leaves, treedef = _tree_paths(tree)
            self.stage_keys.append(keys)
            self.stage_defs.append(treedef)
            for k in keys:
                sig = (tuple(leaves[k].shape), jnp.result_type(leaves[k]))
                if k in self.union and self.union[k] != sig:
                    raise ValueError(
                        f"leaf {k!r} has shape/dtype {sig} on one stage but "
                        f"{self.union[k]} on another; same-named leaves must "
                        "match across stages (rename stage-specific layers)")
                self.union[k] = sig

    def stage_tree(self, stage, union_slice):
        """{path: leaf} union slice -> stage's own pytree."""
        from jax.tree_util import tree_unflatten

        keys = self.stage_keys[stage]
        return tree_unflatten(self.stage_defs[stage],
                              [union_slice[k] for k in keys])

    def embed_grads(self, stage, grads_tree, like):
        """Stage's grad pytree -> union-slice dict (zeros elsewhere)."""
        from jax.tree_util import tree_leaves

        out = {k: jnp.zeros_like(v) for k, v in like.items()}
        for k, g in zip(self.stage_keys[stage], tree_leaves(grads_tree)):
            out[k] = g.astype(like[k].dtype)
        return out


def union_stack(per_stage_params, mesh=None, axis_name="pipe"):
    """Per-stage trees -> ({path: [S, ...] stacked array}, UnionMeta).

    Leaves absent from a stage are zero-filled at that stage's index.
    With ``mesh`` the stacked arrays are placed sharded over the pipe
    axis so each device holds only its stage's slice.
    """
    meta = UnionMeta(per_stage_params)
    stage_leaves = [_tree_paths(tree)[1] for tree in per_stage_params]
    stacked = {}
    for k, (shape, dtype) in meta.union.items():
        stacked[k] = jnp.stack([
            leaves[k] if k in leaves else jnp.zeros(shape, dtype)
            for leaves in stage_leaves])
    if mesh is not None:
        stacked = shard_stacked(mesh, stacked, axis_name)
    return stacked, meta


def union_unstack(stacked, meta):
    """Stacked union -> list of per-stage pytrees (host-side interop)."""
    return [meta.stage_tree(s, {k: v[s] for k, v in stacked.items()})
            for s in range(meta.n_stages)]


def _boundary_chain(stage_fns, meta, stacked, xs_local_sds):
    """Abstract-eval the stage chain; returns (in_sds, out_sds) per stage
    under LOCAL (per-device) batch shapes."""
    in_sds, out_sds = [], []
    cur = xs_local_sds
    for s, fn in enumerate(stage_fns):
        params_aval = meta.stage_tree(s, {
            k: jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
            for k, v in stacked.items()})
        in_sds.append(cur)
        cur = jax.eval_shape(fn, params_aval, cur)
        out_sds.append(cur)
    return in_sds, out_sds


def _flat_len(sds):
    n = 1
    for d in sds.shape[1:]:
        n *= d
    return n


def _boundary_setup(stage_fns, meta, stacked, xs_shape, xs_dtype, S, dp):
    """Shared trace-time setup: abstract-eval the stage chain under local
    batch shapes and size the flat boundary buffer.

    Returns (in_sds, out_sds, F, bdt): per-stage in/out ShapeDtypeStructs,
    the padded per-sample boundary width, and the buffer dtype."""
    xs_local = jax.ShapeDtypeStruct((xs_shape[1] // dp,) + xs_shape[2:],
                                    xs_dtype)
    in_sds, out_sds = _boundary_chain(stage_fns, meta, stacked, xs_local)
    bdtypes = {s.dtype for s in out_sds[:-1]}
    if len(bdtypes) > 1:
        raise ValueError(f"boundary activations mix dtypes {bdtypes}")
    F = max((_flat_len(s) for s in out_sds[:-1]), default=1)
    bdt = out_sds[0].dtype if S > 1 else jnp.float32
    return in_sds, out_sds, F, bdt


def _flatpad(y, F):
    flat = y.reshape(y.shape[0], -1)
    return jnp.pad(flat, ((0, 0), (0, F - flat.shape[1])))


def _unflat(buf, sds):
    n = _flat_len(sds)
    return buf[:, :n].reshape(sds.shape).astype(sds.dtype)


def pipeline_apply_tree(stage_fns, stacked, meta, micro_inputs,
                        mesh: Mesh, axis_name: str = "pipe",
                        data_axis=None):
    """Forward GPipe pass with per-stage trees + shape-changing stages.

    Returns [n_micro, mb, ...] last-stage outputs.  Differentiable: grads
    of a loss on the result flow back through scan+switch+ppermute with
    the GPipe (all-forward-then-all-backward) memory profile; use
    `make_pipeline_train_step` for the O(S)-memory 1F1B schedule.
    """
    S = mesh.shape[axis_name]
    if len(stage_fns) != S:
        raise ValueError(f"{len(stage_fns)} stage fns for {S}-way pipe axis")
    M = micro_inputs.shape[0]
    dp = mesh.shape[data_axis] if data_axis else 1
    ticks = M + S - 1

    in_sds, out_sds, F, bdt = _boundary_setup(
        stage_fns, meta, stacked, micro_inputs.shape, micro_inputs.dtype,
        S, dp)
    y_sds = out_sds[-1]

    branches = []
    for i, fn in enumerate(stage_fns):
        def br(sl, buf_in, x0, i=i, fn=fn):
            p = meta.stage_tree(i, sl)
            x = x0 if i == 0 else _unflat(buf_in, in_sds[i])
            y = fn(p, x)
            if i == S - 1:
                return jnp.zeros((y.shape[0], F), bdt), y
            return _flatpad(y, F).astype(bdt), jnp.zeros(y_sds.shape,
                                                         y_sds.dtype)
        branches.append(br)

    pspecs = {k: P(axis_name, *([None] * (len(sig[0]))))
              for k, sig in meta.union.items()}
    xspec = (P(None, data_axis) if data_axis else P())

    @partial(shard_map, mesh=mesh, in_specs=(pspecs, xspec),
             out_specs=xspec, check_rep=False)
    def run(params, xs):
        sl = {k: v[0] for k, v in params.items()}
        stage = jax.lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        mb = xs.shape[1]

        def tick(buf_in, t):
            m = jnp.clip(t - stage, 0, M - 1)
            x0 = jax.lax.dynamic_index_in_dim(xs, m, keepdims=False)
            flat_out, y = jax.lax.switch(stage, branches, sl, buf_in, x0)
            ok = (t - stage >= 0) & (t - stage < M)
            out = jnp.where(ok & (stage == S - 1), y,
                            jnp.zeros_like(y))
            return jax.lax.ppermute(flat_out, axis_name, fwd_perm), out

        _, outs = jax.lax.scan(tick, jnp.zeros((mb, F), bdt),
                               jnp.arange(ticks))
        outs = outs[S - 1:]  # last stage finishes microbatch t-(S-1)
        return jax.lax.psum(outs, axis_name)

    return run(stacked, micro_inputs)


def make_pipeline_train_step(stage_fns, loss_fn, meta, mesh: Mesh,
                             axis_name: str = "pipe", data_axis=None):
    """Build the fused 1F1B train step.

    stage_fns[i](params_i, x) -> y; loss_fn(y_last, labels) -> scalar
    (mean over its microbatch).  Returns step(stacked, xs, labels) ->
    (loss, grads) where grads is a stacked union dict sharded like the
    params (stage s's grads live on stage s's devices; zeros for leaves a
    stage doesn't own) — feed it straight to a sharded optimizer update,
    or `union_unstack` it for host-side use.

    Schedule: tick t runs fwd(microbatch t-s) and bwd(microbatch
    t+s-(2S-1)) on stage s; boundary inputs are stashed (depth 2S+1) and
    each backward recomputes its stage via jax.vjp — O(S) activation
    memory, (2S-1)/(M+2S-1) lockstep bubble (`bubble_fraction_1f1b`),
    one extra stage forward per microbatch (remat trade).
    """
    S = mesh.shape[axis_name]
    if len(stage_fns) != S:
        raise ValueError(f"{len(stage_fns)} stage fns for {S}-way pipe axis")
    dp = mesh.shape[data_axis] if data_axis else 1
    D = 2 * S + 1  # stash depth: max fwd->bwd gap is 2(S-1)+1 ticks

    def step(stacked, xs, labels):
        M = xs.shape[0]
        ticks = M + 2 * S - 1
        in_sds, out_sds, F, bdt = _boundary_setup(
            stage_fns, meta, stacked, xs.shape, xs.dtype, S, dp)

        fwd_branches, bwd_branches = [], []
        for i, fn in enumerate(stage_fns):
            def fbr(sl, buf_in, x0, lab, i=i, fn=fn):
                p = meta.stage_tree(i, sl)
                x = x0 if i == 0 else _unflat(buf_in, in_sds[i])
                y = fn(p, x)
                if i == S - 1:
                    return (jnp.zeros((x.shape[0], F), bdt),
                            loss_fn(y, lab).astype(jnp.float32))
                return _flatpad(y, F).astype(bdt), jnp.float32(0.0)

            def bbr(sl, x_stash, x0, lab, dy, i=i, fn=fn):
                p = meta.stage_tree(i, sl)
                x = x0 if i == 0 else _unflat(x_stash, in_sds[i])
                if i == S - 1:
                    # loss seeds its own cotangent: 1/M for the
                    # mean-over-microbatches total
                    def g(pp, xx):
                        return loss_fn(fn(pp, xx), lab)
                    _, vjpf = jax.vjp(g, p, x)
                    dparams, dx = vjpf(jnp.float32(1.0 / M))
                else:
                    _, vjpf = jax.vjp(fn, p, x)
                    dparams, dx = vjpf(_unflat(dy, out_sds[i]))
                dunion = meta.embed_grads(i, dparams, sl)
                if i == 0:
                    dxf = jnp.zeros((x.shape[0], F), bdt)
                else:
                    dxf = _flatpad(dx, F).astype(bdt)
                return dunion, dxf

            fwd_branches.append(fbr)
            bwd_branches.append(bbr)

        pspecs = {k: P(axis_name, *([None] * len(sig[0])))
                  for k, sig in meta.union.items()}
        dspec = (P(None, data_axis) if data_axis else P())

        @partial(shard_map, mesh=mesh,
                 in_specs=(pspecs, dspec, dspec),
                 out_specs=(P(), pspecs),
                 check_rep=False)
        def run(params, xs, labels):
            sl = {k: v[0] for k, v in params.items()}
            stage = jax.lax.axis_index(axis_name)
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]
            mb = xs.shape[1]

            def tick(carry, t):
                buf_in, dy_in, stash, gacc, loss_acc = carry
                # ---- forward slot: microbatch t - stage
                fm = t - stage
                do_f = (fm >= 0) & (fm < M)
                mf = jnp.clip(fm, 0, M - 1)
                x0 = jax.lax.dynamic_index_in_dim(xs, mf, keepdims=False)
                lf = jax.lax.dynamic_index_in_dim(labels, mf, keepdims=False)
                flat_out, lc = jax.lax.switch(stage, fwd_branches,
                                              sl, buf_in, x0, lf)
                flat_out = jnp.where(do_f, flat_out,
                                     jnp.zeros_like(flat_out))
                loss_acc = loss_acc + jnp.where(
                    do_f & (stage == S - 1), lc, 0.0)
                # stash this stage's INPUT for the bwd recompute; slot D
                # is a scratch row so out-of-range ticks clobber nothing
                slot = jnp.where(do_f, mf % D, D)
                stash = jax.lax.dynamic_update_index_in_dim(
                    stash, buf_in, slot, 0)
                # ---- backward slot: microbatch t + stage - (2S-1)
                bm = t + stage - (2 * S - 1)
                do_b = (bm >= 0) & (bm < M)
                mbk = jnp.clip(bm, 0, M - 1)
                x0b = jax.lax.dynamic_index_in_dim(xs, mbk, keepdims=False)
                lb = jax.lax.dynamic_index_in_dim(labels, mbk,
                                                  keepdims=False)
                x_st = jax.lax.dynamic_index_in_dim(stash, mbk % D,
                                                    keepdims=False)
                dun, dx = jax.lax.switch(stage, bwd_branches,
                                         sl, x_st, x0b, lb, dy_in)
                gacc = jax.tree_util.tree_map(
                    lambda a, d: a + jnp.where(do_b, d,
                                               jnp.zeros_like(d)),
                    gacc, dun)
                dx = jnp.where(do_b, dx, jnp.zeros_like(dx))
                return ((jax.lax.ppermute(flat_out, axis_name, fwd_perm),
                         jax.lax.ppermute(dx, axis_name, bwd_perm),
                         stash, gacc, loss_acc), None)

            init = (jnp.zeros((mb, F), bdt), jnp.zeros((mb, F), bdt),
                    jnp.zeros((D + 1, mb, F), bdt),
                    {k: jnp.zeros_like(v) for k, v in sl.items()},
                    jnp.float32(0.0))
            (_, _, _, gacc, loss_acc), _ = jax.lax.scan(
                tick, init, jnp.arange(ticks))

            loss = jax.lax.psum(loss_acc, axis_name) / M
            if data_axis:
                loss = jax.lax.pmean(loss, data_axis)
                gacc = {k: jax.lax.pmean(v, data_axis)
                        for k, v in gacc.items()}
            return loss, {k: v[None] for k, v in gacc.items()}

        return run(stacked, xs, labels)

    return jax.jit(step)
