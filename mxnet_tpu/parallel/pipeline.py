"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

The reference approximates pipelining with ctx_group placement + the
dependency engine's opportunistic overlap (docs/how_to/
model_parallel_lstm.md); there is no scheduled-microbatch pipeline.
TPU-native design goes further: stages live on a 'pipe' mesh axis, and
one `shard_map`-wrapped `lax.scan` drives the classic GPipe schedule —
each tick every device runs its stage on the activation `ppermute`d from
the previous stage, so the whole pipeline (fill, steady state, drain) is
ONE XLA program.  Backward falls out of jax autodiff: the transpose of
ppermute is the reverse rotation, giving the mirror-image backward
schedule for free.

Shapes:
- stage parameters are stacked on a leading stage axis and sharded over
  'pipe' (each device holds its stage's slice),
- the microbatched input is [n_micro, micro_batch, ...].

`pipeline_apply` returns the last stage's outputs for every microbatch;
losses/grads compose with jax.value_and_grad around it (see
tests/test_pipeline_moe.py and __graft_entry__.dryrun_multichip §4).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .mesh import shard_map


def stack_stage_params(per_stage_params):
    """[{name: array}, ...] -> {name: array stacked on axis 0} (all stages
    must share parameter structure — the usual 'repeated block' layout)."""
    names = per_stage_params[0].keys()
    return {n: jnp.stack([p[n] for p in per_stage_params]) for n in names}


def shard_stacked(mesh: Mesh, stacked, axis_name: str = "pipe"):
    """Place each stage's parameter slice on its pipeline device."""
    return {
        n: jax.device_put(
            v, NamedSharding(mesh, P(axis_name, *([None] * (v.ndim - 1)))))
        for n, v in stacked.items()
    }


def pipeline_apply(stage_fn, stacked_params, micro_inputs, mesh: Mesh,
                   axis_name: str = "pipe"):
    """Run the GPipe schedule; returns [n_micro, ...] last-stage outputs.

    stage_fn(params_slice, x, stage_index) -> y; every stage must map the
    same activation shape to itself (classic equal-width pipeline).
    stage_index arrives as a traced scalar — use jnp.where/lax.cond on it
    for stage-dependent behavior.
    """
    n_stages = mesh.shape[axis_name]
    n_micro = micro_inputs.shape[0]
    ticks = n_micro + n_stages - 1

    param_specs = {n: P(axis_name, *([None] * (v.ndim - 1)))
                   for n, v in stacked_params.items()}

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, P()),
             out_specs=P(),
             check_rep=False)
    def run(params, xs):
        # params: {name: [1, ...]} my stage's slice; xs: [n_micro, mb, ...]
        my = {n: v[0] for n, v in params.items()}
        stage = jax.lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        act_shape = xs.shape[1:]

        def tick(carry, t):
            held = carry  # activation this device just produced
            # rotate activations one stage forward; stage 0's incoming slot
            # is then overwritten by the next microbatch (or zeros while
            # draining)
            incoming = jax.lax.ppermute(held, axis_name, fwd_perm)
            feed = jnp.where(
                t < n_micro,
                jax.lax.dynamic_index_in_dim(
                    xs, jnp.minimum(t, n_micro - 1), keepdims=False),
                jnp.zeros(act_shape, xs.dtype))
            x_in = jnp.where(stage == 0, feed, incoming)
            y = stage_fn(my, x_in, stage)
            # only the last stage's finished ticks are real outputs
            out = jnp.where(stage == n_stages - 1, y,
                            jnp.zeros_like(y))
            return y, out

        _, outs = jax.lax.scan(tick, jnp.zeros(act_shape, xs.dtype),
                               jnp.arange(ticks))
        # tick t on the last stage finishes microbatch t-(n_stages-1);
        # gather those and share them with every stage (losses are
        # computed replicated)
        outs = outs[n_stages - 1:]
        return jax.lax.psum(outs, axis_name)

    return run(stacked_params, micro_inputs)


def microbatch(x, n_micro):
    """[batch, ...] -> [n_micro, batch/n_micro, ...]."""
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by {n_micro}")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
