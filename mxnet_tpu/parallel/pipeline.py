"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

The reference approximates pipelining with ctx_group placement + the
dependency engine's opportunistic overlap (docs/how_to/
model_parallel_lstm.md); there is no scheduled-microbatch pipeline.
TPU-native design goes further: stages live on a 'pipe' mesh axis, and
one `shard_map`-wrapped `lax.scan` drives the classic GPipe schedule —
each tick every device runs its stage on the activation `ppermute`d from
the previous stage, so the whole pipeline (fill, steady state, drain) is
ONE XLA program.  Backward falls out of jax autodiff: the transpose of
ppermute is the reverse rotation, giving the mirror-image backward
schedule for free.

Schedule & memory profile:
- bubble: (S-1)/(S-1+M) of ticks are fill/drain for S stages and M
  microbatches (`bubble_fraction`); amortize with M >> S.
- activation memory: the autodiff of the scan saves each tick's stage
  activations, i.e. the GPipe profile (O(M) per stage).  1F1B's memory
  advantage (O(S) in-flight microbatches) is obtained here the XLA way:
  pass ``remat=True`` to checkpoint each stage invocation so backward
  recomputes stage activations tick by tick — the scan carry is then the
  only live activation, at ~1/3 extra stage FLOPs (same trade the
  reference exposes as MXNET_BACKWARD_DO_MIRROR, env_var.md:55-57).
- input/output replication: the microbatched input is replicated to all
  stages and outputs are psum-shared (losses are computed replicated) —
  per-device feed memory is O(batch), same order as data-parallel
  training; the per-stage *weights and activations* are what pipelining
  shards.  For feeds too big to replicate, stream microbatches from host
  with a prefetching iterator instead of staging the whole batch.

Real models: stages don't need to be single layers.  The usual layout is
embed/head OUTSIDE the pipeline (computed with plain GSPMD sharding) and
the repeated trunk inside, `blocks_per_stage` blocks per device via
`stacked_blocks_stage` (tests/test_pipeline_moe.py pipelines a 4-block
transformer LM; examples/model-parallel-lstm/lstm_pipeline.py pipelines
the reference's model-parallel LSTM-PTB workload with one LSTM layer per
stage).

Shapes:
- stage parameters are stacked on a leading stage axis and sharded over
  'pipe' (each device holds its stage's slice),
- the microbatched input is [n_micro, micro_batch, ...],
- every stage maps the activation shape to itself (equal-width trunk;
  width changes belong outside the pipelined region).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .mesh import shard_map


def stack_stage_params(per_stage_params):
    """[{name: array}, ...] -> {name: array stacked on axis 0} (all stages
    must share parameter structure — the usual 'repeated block' layout)."""
    names = per_stage_params[0].keys()
    return {n: jnp.stack([p[n] for p in per_stage_params]) for n in names}


def shard_stacked(mesh: Mesh, stacked, axis_name: str = "pipe"):
    """Place each stage's parameter slice on its pipeline device."""
    return {
        n: jax.device_put(
            v, NamedSharding(mesh, P(axis_name, *([None] * (v.ndim - 1)))))
        for n, v in stacked.items()
    }


def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """Fraction of pipeline ticks spent filling/draining (idle bubble):
    (S-1)/(S-1+M).  GPipe and 1F1B share this bubble; they differ only in
    activation memory (see module docstring)."""
    return (n_stages - 1) / (n_stages - 1 + n_micro)


def stacked_blocks_stage(block_fn):
    """Build a stage_fn running `blocks_per_stage` identical blocks.

    block_fn(block_params, x) -> y.  The per-stage parameter slice must
    carry a leading block axis on every leaf ({name: [B, ...]}); the
    blocks run sequentially via lax.scan.  With stack_stage_params the
    full tree is {name: [n_stages, B, ...]} — L = n_stages*B total
    blocks, the standard "repeated trunk" pipeline layout.
    """

    def stage_fn(params, x, stage):
        def body(h, blk):
            return block_fn(blk, h), None

        y, _ = jax.lax.scan(body, x, params)
        return y

    return stage_fn


def pipeline_apply(stage_fn, stacked_params, micro_inputs, mesh: Mesh,
                   axis_name: str = "pipe", remat: bool = False):
    """Run the GPipe schedule; returns [n_micro, ...] last-stage outputs.

    stage_fn(params_slice, x, stage_index) -> y; every stage must map the
    same activation shape to itself (classic equal-width pipeline).
    stage_index arrives as a traced scalar — use jnp.where/lax.cond on it
    for stage-dependent behavior.  remat=True recomputes stage
    activations in backward (1F1B's memory profile; module docstring).
    """
    n_stages = mesh.shape[axis_name]
    n_micro = micro_inputs.shape[0]
    ticks = n_micro + n_stages - 1
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn

    param_specs = {n: P(axis_name, *([None] * (v.ndim - 1)))
                   for n, v in stacked_params.items()}

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, P()),
             out_specs=P(),
             check_rep=False)
    def run(params, xs):
        # params: {name: [1, ...]} my stage's slice; xs: [n_micro, mb, ...]
        my = {n: v[0] for n, v in params.items()}
        stage = jax.lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        act_shape = xs.shape[1:]

        def tick(carry, t):
            held = carry  # activation this device just produced
            # rotate activations one stage forward; stage 0's incoming slot
            # is then overwritten by the next microbatch (or zeros while
            # draining)
            incoming = jax.lax.ppermute(held, axis_name, fwd_perm)
            feed = jnp.where(
                t < n_micro,
                jax.lax.dynamic_index_in_dim(
                    xs, jnp.minimum(t, n_micro - 1), keepdims=False),
                jnp.zeros(act_shape, xs.dtype))
            x_in = jnp.where(stage == 0, feed, incoming)
            y = fn(my, x_in, stage)
            # only the last stage's finished ticks are real outputs
            out = jnp.where(stage == n_stages - 1, y,
                            jnp.zeros_like(y))
            return y, out

        _, outs = jax.lax.scan(tick, jnp.zeros(act_shape, xs.dtype),
                               jnp.arange(ticks))
        # tick t on the last stage finishes microbatch t-(n_stages-1);
        # gather those and share them with every stage (losses are
        # computed replicated)
        outs = outs[n_stages - 1:]
        return jax.lax.psum(outs, axis_name)

    return run(stacked_params, micro_inputs)


def microbatch(x, n_micro):
    """[batch, ...] -> [n_micro, batch/n_micro, ...]."""
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by {n_micro}")
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
