"""Parallelism toolkit: meshes, shardings, collectives, sequence parallel.

TPU-native replacement for the reference's distribution machinery
(SURVEY.md §2.4/§5.8): where MXNet composes engine-scheduled P2P copies +
parameter-server push/pull, this package composes jax.sharding meshes and
XLA collectives over ICI/DCN.
"""
from .mesh import (create_mesh, data_sharding, global_mesh,
                   mesh_shape_from_env, param_shardings, replicated,
                   shard_params, ShardingRule)
