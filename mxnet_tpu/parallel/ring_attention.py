"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence parallelism — long sequences are handled by
bucketing + gradient mirroring (SURVEY.md §5.7).  On TPU, SP is first-class
(SURVEY.md §2.4 'Sequence/context parallelism' row): sequences shard over
the mesh's 'seq' axis and attention runs either as

- ring_attention: K/V blocks rotate around the ring via lax.ppermute while
  each device streams an online-softmax accumulation (blockwise attention;
  the ppermute rides ICI neighbor links, compute overlaps communication
  when XLA schedules the collective-permute asynchronously), or
- ulysses_attention: all-to-all re-shards (seq -> heads), each device runs
  full-sequence attention for its head slice, then all-to-all back.

Both are exact (not approximations) and differentiable (pure jnp/lax, so
jax.vjp handles the backward — the backward ppermutes run in the reverse
ring direction automatically).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..base import mxu_precision
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map


def _stream_block(q, k, v, m, l, o, scale, mask=None):
    """One online-softmax accumulation step (blockwise attention inner op).

    q: (B, H, Tq, D), k/v: (B, H, Tk, D); m/l: (B, H, Tq); o accumulator.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   precision=mxu_precision(q, k)) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (max = -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    p = jnp.exp(s - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v, precision=mxu_precision(p, v))
    return m_new, l_new, o_new


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "seq",
                   causal: bool = False, scale: float = None,
                   batch_axis: str = None):
    """Exact attention over sequence-sharded q/k/v.

    q, k, v: (B, H, T_global, D) arrays sharded over T on `axis_name`.
    Returns output with the same sharding.  ``batch_axis`` additionally
    shards B over a second mesh axis — the standard dp x sp long-context
    layout (each data-parallel replica runs its own ring).
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    n = mesh.shape[axis_name]
    spec = P(batch_axis, None, axis_name, None)

    def local_fn(q, k, v):
        # q/k/v here are the local shards (B_local, H, T/n, D)
        b, h, t_local, _ = q.shape
        idx = jax.lax.axis_index(axis_name)
        m0 = jnp.full((b, h, t_local), -jnp.inf, q.dtype)
        l0 = jnp.zeros((b, h, t_local), q.dtype)
        o0 = jnp.zeros_like(q)

        q_pos = idx * t_local + jnp.arange(t_local)

        def body(step, carry):
            m, l, o, k_cur, v_cur = carry
            src_idx = (idx - step) % n  # whose K/V block we hold this step
            if causal:
                k_pos = src_idx * t_local + jnp.arange(t_local)
                mask = q_pos[:, None] >= k_pos[None, :]
                mask = jnp.broadcast_to(mask, (b, h, t_local, t_local))
            else:
                mask = None
            m, l, o = _stream_block(q, k_cur, v_cur, m, l, o, scale, mask)
            perm = [(i, (i + 1) % n) for i in range(n)]  # pass K/V to next rank
            k_next = jax.lax.ppermute(k_cur, axis_name, perm)
            v_next = jax.lax.ppermute(v_cur, axis_name, perm)
            return (m, l, o, k_next, v_next)

        m, l, o, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
        return o / jnp.maximum(l, 1e-20)[..., None]

    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "seq",
                      causal: bool = False, scale: float = None,
                      batch_axis: str = None):
    """DeepSpeed-Ulysses-style SP: all-to-all (seq->heads), full local
    attention, all-to-all back.  Requires H % mesh.shape[axis] == 0.
    ``batch_axis`` additionally shards B over a second mesh axis (dp x
    sp; the all-to-alls stay within each data replica's 'seq' group)."""
    h, d = q.shape[1], q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    n = mesh.shape[axis_name]
    if h % n != 0:
        raise ValueError(f"heads {h} not divisible by seq-par degree {n}")
    spec = P(batch_axis, None, axis_name, None)

    def local_fn(q, k, v):
        # local: (B, H, T/n, D) -> a2a -> (B, H/n, T, D)
        def a2a(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                      tiled=True)

        ql, kl, vl = a2a(q), a2a(k), a2a(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", ql, kl,
                       precision=mxu_precision(ql, kl)) * scale
        if causal:
            tq = s.shape[-2]
            mask = jnp.tril(jnp.ones((tq, tq), bool))
            s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        ol = jnp.einsum("bhqk,bhkd->bhqd", p, vl, precision=mxu_precision(p, vl))
        # back: (B, H/n, T, D) -> (B, H, T/n, D)
        return jax.lax.all_to_all(ol, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def full_attention(q, k, v, causal=False, scale=None):
    """Single-device reference attention (the oracle for SP tests) —
    materializes the (T, T) score matrix; use :func:`attention` for the
    memory-efficient dispatcher."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   precision=mxu_precision(q, k)) * scale
    if causal:
        t = s.shape[-1]
        mask = jnp.tril(jnp.ones((s.shape[-2], t), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v, precision=mxu_precision(p, v))


def attention(q, k, v, causal=False, scale=None, impl="auto", platform=None):
    """Single-device attention dispatcher.

    impl='flash' (or 'auto' on TPU with block-compatible shapes) runs the
    Pallas flash kernels (ops/flash_attention.py) — O(T·D) memory, score
    tiles live only in VMEM.  Everything else falls back to the lax path
    (XLA still fuses well, but the (T, T) scores hit HBM).

    ``platform`` is the platform this call will lower FOR (threaded from
    OpCtx by the symbol-graph path); None falls back to the process
    default backend.  The distinction matters whenever a computation
    targets non-default devices — a CPU mesh on a TPU-attached host
    would otherwise pick the Pallas kernel and fail to lower."""
    from ..ops import flash_attention as fa

    # kernel tile sizes are a measured quantity, not a constant:
    # MXTPU_FLASH_BLOCK_Q/K let the on-silicon sweeps
    # (tools/probe_lm_mfu.py) tune them without code edits.  Clamped to
    # T (matching flash_attention's own clamp) BEFORE the supports()
    # check so an oversized tile cannot silently demote a
    # flash-compatible shape to the O(T^2) lax path.
    bq = min(_env_block("MXTPU_FLASH_BLOCK_Q"), q.shape[2])
    bk = min(_env_block("MXTPU_FLASH_BLOCK_K"), q.shape[2])
    if impl == "auto":
        on_tpu = (platform or jax.default_backend()) == "tpu"
        impl = "flash" if on_tpu and fa.supports(q.shape, bq, bk) else "lax"
    if impl == "flash":
        return fa.flash_attention(q, k, v, causal, scale, bq, bk)
    if impl == "flash_interpret":  # CPU test path for the kernels
        return fa.flash_attention(q, k, v, causal, scale, bq, bk, True)
    return full_attention(q, k, v, causal=causal, scale=scale)


def _env_block(name, default=128):
    """Tile-size env knob: malformed or non-positive values fall back to
    the default with a warning instead of crashing unrelated paths."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        val = 0
    if val <= 0:
        import warnings

        warnings.warn(f"{name}={raw!r} is not a positive integer; "
                      f"using {default}", stacklevel=3)
        return default
    return val
