"""Mesh + sharding helpers.

The reference maps devices via ctx lists and ctx_group attrs
(kvstore/comm.h device placement, graph_executor.cc PlaceDevice).  Here a
jax.sharding.Mesh with named axes is the single source of truth:

- 'data'  : batch (data parallel — kvstore local/device parity)
- 'model' : tensor parallel (no reference analogue; SURVEY.md §2.4 marks
            TP as absent upstream — first-class here)
- 'pipe'  : pipeline stages (ctx_group parity)
- 'seq'   : sequence/context parallel (ring attention)
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_mesh(shape=None, axes=("data",), devices=None) -> Mesh:
    """Build a Mesh from the available devices.

    create_mesh() -> 1-D data mesh over all devices;
    create_mesh((4, 2), ("data", "model")) -> 2-D dp x tp mesh.
    """
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, axes)


def data_sharding(mesh: Mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Batch-dim sharding for an ndim array."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@dataclass
class ShardingRule:
    """Regex -> PartitionSpec rule for parameter sharding (the TP analogue
    of the reference's ctx_group model-parallel annotations)."""

    pattern: str
    spec: tuple

    def matches(self, name: str) -> bool:
        return re.match(self.pattern, name) is not None


def shard_params(mesh: Mesh, params: dict, rules: Sequence[ShardingRule] = ()) -> dict:
    """device_put every param according to the first matching rule
    (default: replicated)."""
    out = {}
    for name, arr in params.items():
        spec = P()
        for rule in rules:
            if rule.matches(name):
                spec = P(*rule.spec)
                break
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out


def shard_map(f=None, **kw):
    """jax.shard_map with the old `check_rep` kwarg accepted (new API
    spells it `check_vma`); shared by pipeline/moe/ring_attention."""
    import jax

    kw["check_vma"] = kw.pop("check_rep", kw.pop("check_vma", True))
    return jax.shard_map(f, **kw) if f is not None else jax.shard_map(**kw)
