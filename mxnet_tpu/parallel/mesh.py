"""Mesh + sharding helpers.

The reference maps devices via ctx lists and ctx_group attrs
(kvstore/comm.h device placement, graph_executor.cc PlaceDevice).  Here a
jax.sharding.Mesh with named axes is the single source of truth:

- 'data'  : batch (data parallel — kvstore local/device parity)
- 'model' : tensor parallel (no reference analogue; SURVEY.md §2.4 marks
            TP as absent upstream — first-class here)
- 'pipe'  : pipeline stages (ctx_group parity)
- 'seq'   : sequence/context parallel (ring attention)
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_mesh(shape=None, axes=("data",), devices=None) -> Mesh:
    """Build a Mesh from the available devices.

    create_mesh() -> 1-D data mesh over all devices;
    create_mesh((4, 2), ("data", "model")) -> 2-D dp x tp mesh.
    """
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, axes)


def data_sharding(mesh: Mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Batch-dim sharding for an ndim array."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@dataclass
class ShardingRule:
    """Regex -> PartitionSpec rule for parameter sharding (the TP analogue
    of the reference's ctx_group model-parallel annotations)."""

    pattern: str
    spec: tuple

    def matches(self, name: str) -> bool:
        return re.match(self.pattern, name) is not None


def shard_params(mesh: Mesh, params: dict, rules: Sequence[ShardingRule] = ()) -> dict:
    """device_put every param according to the first matching rule
    (default: replicated)."""
    out = {}
    for name, arr in params.items():
        spec = P()
        for rule in rules:
            if rule.matches(name):
                spec = P(*rule.spec)
                break
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out


def megatron_rules(model_axis: str = "model", shard_embed: bool = True):
    """Megatron-style tensor-parallel sharding rules for the transformer
    zoo (models/transformer.py naming).

    The classic layout (Shoeybi et al.): attention qkv and FFN-in are
    *column*-parallel (split the output features: weight rows, since
    FullyConnected weights are (out, in)), their biases split with them;
    the attention out-projection and FFN-out are *row*-parallel (split
    the input features: weight columns) with replicated biases — GSPMD
    then inserts exactly one all-reduce after each row-parallel matmul,
    matching Megatron's f/g collectives.  The LM head and token embedding
    shard over the vocab dim.

    Returns a tuple of ShardingRule for FusedTrainer(sharding_rules=...)
    / shard_params.  No reference analogue: SURVEY.md §2.4 marks TP
    absent upstream.
    """
    rules = [
        # attention: q/k/v column-parallel, out-projection row-parallel
        # (separate projections so the shard boundary never cuts a packed
        # q|k|v layout — models/transformer.py)
        ShardingRule(r".*_(q|k|v)_weight$", (model_axis, None)),
        ShardingRule(r".*_(q|k|v)_bias$", (model_axis,)),
        ShardingRule(r".*_proj_weight$", (None, model_axis)),
        # FFN: in column-parallel, out row-parallel
        ShardingRule(r".*_ffn_in_weight$", (model_axis, None)),
        ShardingRule(r".*_ffn_in_bias$", (model_axis,)),
        ShardingRule(r".*_ffn_out_weight$", (None, model_axis)),
        # LM head: vocab-dim column-parallel
        ShardingRule(r"lm_head_weight$", (model_axis, None)),
        ShardingRule(r"lm_head_bias$", (model_axis,)),
    ]
    if shard_embed:
        rules.append(ShardingRule(r"tok_embed_weight$", (model_axis, None)))
    return tuple(rules)


def shard_map(f=None, **kw):
    """jax.shard_map across jax versions: the new top-level API spells
    the replication check `check_vma`, the 0.4.x experimental API spells
    it `check_rep` (and has no top-level export).  Shared by
    pipeline/moe/ring_attention so version skew lives in ONE place."""
    import functools

    import jax

    if f is None:
        return functools.partial(shard_map, **kw)
    check = kw.pop("check_rep", kw.pop("check_vma", True))
    impl = getattr(jax, "shard_map", None)
    if impl is not None:
        return impl(f, check_vma=check, **kw)
    from jax.experimental.shard_map import shard_map as impl

    return impl(f, check_rep=check, **kw)
