"""Mesh + sharding helpers.

The reference maps devices via ctx lists and ctx_group attrs
(kvstore/comm.h device placement, graph_executor.cc PlaceDevice).  Here a
jax.sharding.Mesh with named axes is the single source of truth:

- 'data'  : batch (data parallel — kvstore local/device parity)
- 'model' : tensor parallel (no reference analogue; SURVEY.md §2.4 marks
            TP as absent upstream — first-class here)
- 'pipe'  : pipeline stages (ctx_group parity)
- 'seq'   : sequence/context parallel (ring attention)
"""
from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import telemetry as _tm

# docs/telemetry.md — set whenever a process mesh is (re)built, one
# sample per axis; the scrapeable record of the topology a run used
_TM_AXIS = _tm.gauge(
    "mesh_axis_size",
    "size of each axis of the process-level device mesh "
    "(MXTPU_MESH_SHAPE; set at global_mesh build)", labels=("axis",))


def create_mesh(shape=None, axes=("data",), devices=None) -> Mesh:
    """Build a Mesh from the available devices.

    create_mesh() -> 1-D data mesh over all devices;
    create_mesh((4, 2), ("data", "model")) -> 2-D dp x tp mesh.

    One axis may be ``-1`` (inferred from the device count).  A shape
    the devices cannot fill raises :class:`MXNetError` naming the
    counts — the raw ``reshape`` error a bad ``MXTPU_MESH_SHAPE`` used
    to surface names neither the shape nor the device count.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    shape = tuple(int(s) for s in shape)
    if len(shape) != len(axes):
        raise MXNetError(
            f"mesh shape {shape} has {len(shape)} dims for "
            f"{len(axes)} axes {tuple(axes)}")
    if sum(1 for s in shape if s == -1) > 1:
        raise MXNetError(f"mesh shape {shape}: at most one -1 axis")
    if any(s == 0 or s < -1 for s in shape):
        raise MXNetError(f"mesh shape {shape}: axis sizes must be "
                         "positive (or one -1 to infer)")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if known <= 0 or len(devices) % known != 0:
            raise MXNetError(
                f"mesh shape {shape}: cannot infer -1 axis — "
                f"{len(devices)} devices not divisible by {known}")
        shape = tuple(len(devices) // known if s == -1 else s
                      for s in shape)
    n = int(np.prod(shape))
    if n > len(devices):
        raise MXNetError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, axes)


# ---------------------------------------------------------------------------
# Process-level mesh (the GSPMD backend's single source of device truth).
#
# One logical 2-D mesh ("batch", "model") covers the process's devices:
# the executor group shards input batches over "batch", group2ctx
# PartitionSpec annotations place parameters over "model", and the
# sharded fused optimizer update (kvstore_fused) splits every flat
# bucket across the whole mesh per arXiv:2004.13336.  MXTPU_MESH_SHAPE
# ("8,1", "4,2", "-1,2", ...) picks the factorization; the default is
# pure data parallel (n_devices, 1).  The same code runs from 8 chips
# to pod slices — only this env var changes.
# ---------------------------------------------------------------------------
GLOBAL_AXES = ("batch", "model")
_global_mesh_cache = {}
_global_mesh_lock = threading.Lock()


def mesh_shape_from_env(n_devices: int):
    """Resolved MXTPU_MESH_SHAPE as a tuple (default (n_devices, 1))."""
    raw = os.environ.get("MXTPU_MESH_SHAPE", "").strip()
    if not raw:
        return (n_devices, 1)
    parts = [p for p in re.split(r"[,x\s]+", raw.strip("()[]")) if p]
    try:
        shape = tuple(int(p) for p in parts)
    except ValueError:
        raise MXNetError(f"MXTPU_MESH_SHAPE={raw!r}: expected integers "
                         "like '8,1' or '4,2'")
    if len(shape) == 1:
        shape = (shape[0], 1)
    if len(shape) != 2:
        raise MXNetError(f"MXTPU_MESH_SHAPE={raw!r}: the process mesh "
                         f"is 2-D {GLOBAL_AXES}, got {len(shape)} dims")
    return shape


def global_mesh(devices=None) -> Mesh:
    """The process-level ("batch", "model") mesh over ``devices``
    (default: all devices).  Cached per (env shape, device list); the
    ``mesh_axis_size`` gauge records the axes of the last build."""
    devices = list(devices) if devices is not None else jax.devices()
    raw = os.environ.get("MXTPU_MESH_SHAPE", "").strip()
    key = (raw, tuple(id(d) for d in devices))
    with _global_mesh_lock:
        mesh = _global_mesh_cache.get(key)
    if mesh is not None:
        return mesh
    shape = mesh_shape_from_env(len(devices))
    n = int(np.prod([s for s in shape if s != -1]))
    if -1 not in shape and len(devices) % n != 0:
        raise MXNetError(
            f"MXTPU_MESH_SHAPE={shape} needs a multiple of {n} devices, "
            f"have {len(devices)}")
    mesh = create_mesh(shape, GLOBAL_AXES, devices=devices)
    with _global_mesh_lock:
        _global_mesh_cache[key] = mesh
    if _tm.enabled():
        for axis, size in zip(GLOBAL_AXES, mesh.devices.shape):
            _TM_AXIS.set(size, axis=axis)
    return mesh


def data_sharding(mesh: Mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Batch-dim sharding for an ndim array."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@dataclass
class ShardingRule:
    """Regex -> PartitionSpec rule for parameter sharding (the TP analogue
    of the reference's ctx_group model-parallel annotations)."""

    pattern: str
    spec: tuple

    def matches(self, name: str) -> bool:
        return re.match(self.pattern, name) is not None


def param_shardings(mesh: Mesh, names, rules: Sequence[ShardingRule] = ()) -> dict:
    """{name: NamedSharding} from the first matching rule per name
    (default: replicated over ``mesh``)."""
    out = {}
    for name in names:
        spec = P()
        for rule in rules:
            if rule.matches(name):
                spec = P(*rule.spec)
                break
        out[name] = NamedSharding(mesh, spec)
    return out


def shard_params(mesh: Mesh, params: dict, rules: Sequence[ShardingRule] = ()) -> dict:
    """Place every param according to the first matching rule (default:
    replicated over ``mesh``).

    The whole dict moves through ONE batched ``jax.device_put`` (one
    transfer program instead of one dispatch per param); entries whose
    sharding already equals their target pass through untouched — the
    micro-assert below pins that re-sharding an already-correctly-
    sharded dict is a no-op, so callers may re-apply rules defensively
    (e.g. a rebind) without paying a transfer.
    """
    shardings = param_shardings(mesh, params.keys(), rules)
    done, todo = {}, {}
    for name, arr in params.items():
        if isinstance(arr, jax.Array) and arr.sharding == shardings[name]:
            done[name] = arr
        else:
            todo[name] = arr
    if todo:
        moved = jax.device_put(todo, {k: shardings[k] for k in todo})
        done.update(moved)
    out = {name: done[name] for name in params}
    for name, arr in params.items():
        if isinstance(arr, jax.Array) and arr.sharding == shardings[name]:
            assert out[name] is arr, (
                f"shard_params: re-sharding already-placed param {name!r} "
                "must be a no-op")
    return out


def megatron_rules(model_axis: str = "model", shard_embed: bool = True):
    """Megatron-style tensor-parallel sharding rules for the transformer
    zoo (models/transformer.py naming).

    The classic layout (Shoeybi et al.): attention qkv and FFN-in are
    *column*-parallel (split the output features: weight rows, since
    FullyConnected weights are (out, in)), their biases split with them;
    the attention out-projection and FFN-out are *row*-parallel (split
    the input features: weight columns) with replicated biases — GSPMD
    then inserts exactly one all-reduce after each row-parallel matmul,
    matching Megatron's f/g collectives.  The LM head and token embedding
    shard over the vocab dim.

    Returns a tuple of ShardingRule for FusedTrainer(sharding_rules=...)
    / shard_params.  No reference analogue: SURVEY.md §2.4 marks TP
    absent upstream.
    """
    rules = [
        # attention: q/k/v column-parallel, out-projection row-parallel
        # (separate projections so the shard boundary never cuts a packed
        # q|k|v layout — models/transformer.py)
        ShardingRule(r".*_(q|k|v)_weight$", (model_axis, None)),
        ShardingRule(r".*_(q|k|v)_bias$", (model_axis,)),
        ShardingRule(r".*_proj_weight$", (None, model_axis)),
        # FFN: in column-parallel, out row-parallel
        ShardingRule(r".*_ffn_in_weight$", (model_axis, None)),
        ShardingRule(r".*_ffn_in_bias$", (model_axis,)),
        ShardingRule(r".*_ffn_out_weight$", (None, model_axis)),
        # LM head: vocab-dim column-parallel
        ShardingRule(r"lm_head_weight$", (model_axis, None)),
        ShardingRule(r"lm_head_bias$", (model_axis,)),
    ]
    if shard_embed:
        rules.append(ShardingRule(r"tok_embed_weight$", (model_axis, None)))
    return tuple(rules)


def shard_map(f=None, **kw):
    """jax.shard_map across jax versions: the new top-level API spells
    the replication check `check_vma`, the 0.4.x experimental API spells
    it `check_rep` (and has no top-level export).  Shared by
    pipeline/moe/ring_attention so version skew lives in ONE place."""
    import functools

    import jax

    if f is None:
        return functools.partial(shard_map, **kw)
    check = kw.pop("check_rep", kw.pop("check_vma", True))
    impl = getattr(jax, "shard_map", None)
    if impl is not None:
        return impl(f, check_vma=check, **kw)
    from jax.experimental.shard_map import shard_map as impl

    return impl(f, check_rep=check, **kw)
