"""Cluster coordinator — generation-epoch membership that survives host
death (the ``dist_async``/elastic control plane of docs/multihost.md).

The paper's parameter server tracked liveness through ps-lite scheduler
heartbeats (``KVStore::get_num_dead_node``); on TPU pods the synchronous
data path needs no server, but *membership* still needs an authority:
who is in the cluster, which epoch ("generation") of the cluster is
current, and who died.  This module is that authority, riding the same
stdlib-HTTP skeleton as the telemetry ``/metrics`` endpoint:

- :class:`CoordinatorService` — rank 0 (or the elastic launcher) hosts
  it on ``MXTPU_COORD_PORT``.  Members hold **leases**
  (``MXTPU_COORD_LEASE_S``) refreshed by heartbeats on a dedicated
  thread (the kvstore_server heartbeat/``MXTPU_PS_DEAD_TIMEOUT_S``
  shape, generalized); a lease that expires declares the host dead,
  records it, and **publishes the next generation**.  ``GET /cluster``
  is the operator's status JSON.
- **fleet observability** (ISSUE 14, telemetry/fleet.py): joins carry
  each member's telemetry endpoint, so the coordinator federates every
  member's ``/metrics.json`` on a background scrape thread and serves
  the merged, host-labeled view at ``GET /fleet``
  (``tools/fleetstat.py`` is the operator CLI).  Heartbeats carry
  per-step wall/dispatch timings from the flight-recorder ring; the
  lease monitor computes the per-generation step-time skew, publishes
  ``dist_step_skew_ratio`` / ``dist_straggler_host``, and names a
  sustained straggler in ``/cluster`` and ``/fleet``.  Heartbeat
  replies carry the coordinator's wall clock, and the client records
  the RTT-midpoint clock offset into the flight record so
  ``fleetstat.py merge-trace`` puts per-host lanes on one timebase.
- :class:`CoordinatorClient` — every worker joins, heartbeats in the
  background, and polls :meth:`CoordinatorClient.step_poll` from the
  training loop (pure host-side flag check — nothing on the hot path
  touches the device).  A published generation != the joined one means
  the membership changed: the loop checkpoints at the boundary and
  raises :class:`~mxnet_tpu.parallel.dist.GenerationChanged`.  A worker
  wedged inside a dead collective can never reach the next poll, so the
  heartbeat thread doubles as the **barrier watchdog**: once a change
  is published and the loop stays silent past
  ``MXTPU_DIST_BARRIER_TIMEOUT_S``, it dumps the flight record and
  exits :data:`~mxnet_tpu.parallel.dist.EXIT_HOST_LOST` — the one exit
  jax.distributed leaves open (see parallel/dist.py).

Fault sites (docs/fault_tolerance.md): ``coord_heartbeat`` (drop =
lost heartbeats → lease expiry at the service), ``host_crash``
(``crash_after:n`` = a SIGKILL-shaped death mid-training for chaos
tests).

Every RPC carries a socket timeout; an unreachable coordinator
surfaces as a named :class:`~mxnet_tpu.parallel.dist.HostLostError`
(site=coordinator) at the next loop boundary, never a hang.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time

from ..base import MXNetError
from .. import telemetry as _tm
from ..telemetry import fleet as _fleet
from .dist import (EXIT_HOST_LOST, GenerationChanged, HostLostError,
                   barrier_timeout_s)

__all__ = ["CoordinatorService", "CoordinatorClient", "coord_lease_s",
           "coord_addr", "maybe_start_from_env", "client_from_env"]

_logger = logging.getLogger("mxnet_tpu.parallel.coordinator")

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_GEN = _tm.gauge(
    "dist_generation",
    "current cluster generation epoch (bumped on every membership "
    "change: lease-expiry death, rejoin announcement, clean leave)")
_TM_ALIVE = _tm.gauge(
    "dist_hosts_alive",
    "hosts holding a live coordinator lease in the current generation")
_TM_EXPIRED = _tm.counter(
    "coordinator_lease_expired_total",
    "host leases the coordinator declared dead (no heartbeat within "
    "MXTPU_COORD_LEASE_S); each expiry publishes the next generation")


def coord_lease_s() -> float:
    """MXTPU_COORD_LEASE_S — membership lease (default 10s).  Heartbeats
    go every lease/3; a host silent for a full lease is declared dead."""
    try:
        return max(float(os.environ.get("MXTPU_COORD_LEASE_S", "10")), 0.2)
    except ValueError:
        return 10.0


def coord_addr():
    """MXTPU_COORD_ADDR — ``host:port`` of the coordinator service (set
    by the elastic launcher), or None."""
    return os.environ.get("MXTPU_COORD_ADDR", "").strip() or None


class CoordinatorService:
    """Membership + generation authority (one per cluster, on rank 0 or
    the elastic launcher).  Thread-safe; start() binds the HTTP server
    on a daemon thread and returns self."""

    def __init__(self, port=None, lease_s=None, generation=0):
        self.lease_s = coord_lease_s() if lease_s is None else float(lease_s)
        self.port = int(os.environ.get("MXTPU_COORD_PORT", "0") or 0) \
            if port is None else int(port)
        self._lock = threading.Lock()
        self.generation = int(generation)
        # member id -> {host, pid, rank, beat (monotonic), generation}
        self._members = {}
        # members announced for the NEXT generation (rejoiners): they
        # hold no lease yet — they enter when the launcher relaunches
        self._standby = {}
        self._dead = []      # [{member, host, generation, time}]
        self._events = []    # bounded human-readable history
        self._srv = None
        self._stop = threading.Event()
        self._monitor = None
        self.started = time.time()
        # fleet plane (ISSUE 14): metrics federation over the members'
        # advertised telemetry endpoints + step-skew straggler state
        self.scraper = _fleet.FleetScraper(self._scrape_targets)
        self._straggler = None        # flagged {member, host, ratio, ...}
        self._skew = 0.0              # latest skew ratio (worst/median)
        self._strag_streaks = {}      # member -> consecutive hot sweeps

    # -- state transitions (all under _lock) -------------------------------
    def _bump(self, why):
        # race-ok: every caller (join/leave/expire_leases) already holds
        # self._lock around this helper; it is never called bare
        self.generation += 1
        self._events.append(
            {"time": time.time(), "generation": self.generation,
             "why": why})
        del self._events[:-64]
        if _tm.enabled():
            _TM_GEN.set(self.generation)
            _TM_ALIVE.set(len(self._members))
        _logger.warning("coordinator: generation -> %d (%s)",
                        self.generation, why)

    def join(self, member, host="?", pid=0, rank=-1, generation=None,
             standby=False, telemetry_addr=None, role="train"):
        """Register a member.  A normal join enters the CURRENT
        generation (bring-up: the launcher started this world).  A
        ``standby`` join is a rejoin announcement: the host is back but
        must enter at the next generation boundary — it is recorded,
        the generation is bumped so running members leave their step
        loops at the boundary, and the launcher relaunches everyone.
        ``telemetry_addr`` (``host:port`` of the member's /metrics
        server) opts the member into the fleet federation scrape.
        ``role`` distinguishes training hosts (``"train"``) from
        serving replicas (``"serve"`` — ISSUE 15): the serving router
        folds ``role="serve"`` members into its replica registry, so a
        replica's lease IS its registration."""
        with self._lock:
            info = {"host": host, "pid": int(pid), "rank": int(rank),
                    "beat": time.monotonic(),
                    "role": str(role or "train"),
                    "telemetry": (str(telemetry_addr)
                                  if telemetry_addr else None),
                    "generation": self.generation if generation is None
                    else int(generation)}
            if standby:
                self._standby[member] = info
                self._bump(f"rejoin announced: {member}")
            else:
                self._members[member] = info
                self._standby.pop(member, None)
                if _tm.enabled():
                    _TM_ALIVE.set(len(self._members))
                    _TM_GEN.set(self.generation)
            return {"generation": self.generation,
                    "lease_s": self.lease_s, "ok": True}

    def heartbeat(self, member, generation=None, progress=None, steps=None):
        with self._lock:
            m = self._members.get(member)
            if m is not None:
                m["beat"] = time.monotonic()
                if progress is not None:
                    # batches trained this incarnation: the elastic
                    # launcher gates rejoin announcements on the shrunk
                    # world having made real progress
                    m["progress"] = int(progress)
                if isinstance(steps, dict):
                    # per-step wall/dispatch timings from the member's
                    # flight ring — the straggler-detection feed
                    m["steps"] = {k: float(v) for k, v in steps.items()
                                  if isinstance(v, (int, float))
                                  and not isinstance(v, bool)}
            # server_time lets the member estimate its clock offset from
            # the RTT midpoint (merge-trace's common timebase)
            return {"generation": self.generation,
                    "server_time": time.time(),
                    "ok": m is not None
                    and (generation is None
                         or int(generation) == self.generation)}

    def leave(self, member, why="leave"):
        with self._lock:
            was = self._members.pop(member, None)
            self._standby.pop(member, None)
            if was is not None and self._members:
                # remaining members must react to the shrink; an empty
                # cluster (normal completion) has nobody left to tell
                self._bump(f"{why}: {member}")
            elif _tm.enabled():
                _TM_ALIVE.set(len(self._members))
            return {"generation": self.generation, "ok": was is not None}

    def advance(self, generation, why="relaunch"):
        """Launcher-driven generation sync: the elastic launcher is
        about to (re)launch the world as ``generation``.  The service
        adopts the counter (never going backwards) and clears every
        stale lease and standby entry — members of dead incarnations
        must not expire INTO the new generation and push it out."""
        with self._lock:
            self.generation = max(self.generation, int(generation))
            self._members.clear()
            self._standby.clear()
            self._events.append(
                {"time": time.time(), "generation": self.generation,
                 "why": why})
            del self._events[:-64]
            if _tm.enabled():
                _TM_GEN.set(self.generation)
                _TM_ALIVE.set(0)
            return {"generation": self.generation, "ok": True}

    def expire_leases(self):
        """Declare members whose lease lapsed dead; one generation bump
        per sweep (a simultaneous multi-host failure is ONE membership
        change).  Called by the monitor thread and by tests."""
        now = time.monotonic()
        with self._lock:
            dead = [mid for mid, m in self._members.items()
                    if now - m["beat"] > self.lease_s]
            for mid in dead:
                m = self._members.pop(mid)
                self._dead.append({"member": mid, "host": m["host"],
                                   "generation": m["generation"],
                                   "time": time.time()})
                del self._dead[:-64]
                if _tm.enabled():
                    _TM_EXPIRED.inc()
                _logger.warning(
                    "coordinator: lease expired for %s (host %s) — "
                    "declared dead", mid, m["host"])
            if dead:
                self._bump("lease expired: " + ",".join(sorted(dead)))
            return dead

    # -- fleet plane (ISSUE 14) ---------------------------------------------
    def _scrape_targets(self):
        """Live members' advertised telemetry endpoints (the federation
        sweep's target list — dead leases drop out automatically)."""
        with self._lock:
            return {mid: m["telemetry"] for mid, m in self._members.items()
                    if m.get("telemetry")}

    def eval_straggler(self):
        """Per-generation straggler detection from heartbeat timings.

        Skew = the slowest member's mean step wall over the fleet
        median.  A member above ``MXTPU_STRAGGLER_RATIO`` for
        ``STRAGGLER_SUSTAIN`` consecutive monitor sweeps (one GC pause
        is not a sick host) is *named*: logged, flagged in ``/cluster``
        and ``/fleet``, and set in ``dist_straggler_host``.  Called by
        the lease-monitor thread every lease/4 — detection latency is
        well inside one federation scrape interval."""
        import statistics

        with self._lock:
            stats = {}
            for mid, m in self._members.items():
                s = m.get("steps") or {}
                if (s.get("count", 0) >= _fleet.STRAGGLER_MIN_STEPS
                        and s.get("step_wall_s", 0) > 0):
                    stats[mid] = float(s["step_wall_s"])
            if len(stats) < 2:
                self._set_straggler(None, 0.0)
                return None
            worst = max(stats, key=stats.get)
            # the fleet median EXCLUDES the candidate: on a 2-host world
            # max/median(all) is bounded below 2x no matter how sick the
            # slow host is, which would blind the default threshold
            median = statistics.median(
                [v for mid, v in stats.items() if mid != worst])
            ratio = stats[worst] / median if median > 0 else 0.0
            threshold = _fleet.straggler_ratio()
            if threshold > 1.0 and ratio >= threshold:
                self._strag_streaks = {
                    worst: self._strag_streaks.get(worst, 0) + 1}
            else:
                self._strag_streaks = {}
            flagged = (self._strag_streaks.get(worst, 0)
                       >= _fleet.STRAGGLER_SUSTAIN)
            info = None
            if flagged:
                m = self._members[worst]
                info = {"member": worst, "host": m["host"],
                        "rank": m["rank"], "generation": self.generation,
                        "step_wall_s": round(stats[worst], 6),
                        "fleet_median_s": round(median, 6),
                        "ratio": round(ratio, 3)}
            self._set_straggler(info, ratio)
            return self._straggler

    def _set_straggler(self, info, ratio):
        # every caller (eval_straggler) already holds self._lock around
        # this helper; it is never called bare
        self._skew = float(ratio)  # race-ok: caller holds self._lock
        prev = self._straggler
        self._straggler = info  # race-ok: caller holds self._lock
        if _tm.enabled():
            _fleet._TM_SKEW.set(self._skew)
            if prev and (info is None or info["member"] != prev["member"]):
                _fleet._TM_STRAGGLER.set(0, host=prev["member"])
            if info:
                _fleet._TM_STRAGGLER.set(1, host=info["member"])
        if info and (prev is None or prev["member"] != info["member"]):
            _logger.warning(
                "coordinator: straggler detected: %s (host %s) at %.2fx "
                "the fleet median step time (%.1fms vs %.1fms)",
                info["member"], info["host"], info["ratio"],
                info["step_wall_s"] * 1e3, info["fleet_median_s"] * 1e3)

    def fleet(self):
        """The ``GET /fleet`` JSON: per-host rows (membership + latest
        scrape status + heartbeat step timings), the merged host-labeled
        metric families, and the generation/straggler state — the one
        view that used to be N disconnected dashboards."""
        cl = self.cluster()
        snaps = self.scraper.snapshot()
        hosts = {}
        for mid, m in cl["members"].items():
            row = dict(m)
            s = snaps.get(mid)
            row["scrape_ok"] = bool(s and s.get("ok"))
            if s:
                row["scraped_at"] = s.get("at")
                if s.get("error"):
                    row["scrape_error"] = s["error"]
            hosts[mid] = row
        merged = _fleet.merge_snapshots(
            {mid: s.get("metrics") or {} for mid, s in snaps.items()
             if s.get("ok") and mid in cl["members"]})
        return {
            "generation": cl["generation"],
            "hosts_alive": cl["hosts_alive"],
            "straggler": cl["straggler"],
            "step_skew_ratio": cl["step_skew_ratio"],
            "scrape_interval_s": self.scraper.interval_s,
            "dead": cl["dead"],
            "hosts": hosts,
            "metrics": merged,
        }

    def cluster(self):
        """The ``/cluster`` status JSON."""
        now = time.monotonic()
        with self._lock:
            return {
                "generation": self.generation,
                "lease_s": self.lease_s,
                "hosts_alive": len(self._members),
                "members": {
                    mid: {"host": m["host"], "pid": m["pid"],
                          "rank": m["rank"],
                          "role": m.get("role", "train"),
                          "joined_generation": m["generation"],
                          "progress": m.get("progress", 0),
                          "telemetry": m.get("telemetry"),
                          "steps": m.get("steps"),
                          "lease_age_s": round(now - m["beat"], 3)}
                    for mid, m in self._members.items()},
                "standby": sorted(self._standby),
                "dead": list(self._dead),
                "straggler": self._straggler,
                "step_skew_ratio": round(self._skew, 3),
                "events": list(self._events),
                "uptime_s": round(time.time() - self.started, 3),
            }

    # -- HTTP ---------------------------------------------------------------
    def start(self, addr="127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        svc = self

        class _Handler(BaseHTTPRequestHandler):
            def _reply(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path in ("/", "/cluster"):
                    self._reply(svc.cluster())
                elif path == "/fleet":
                    self._reply(svc.fleet())
                elif path == "/healthz":
                    self._reply({"status": "ok",
                                 "generation": svc.generation})
                else:
                    self.send_error(404)

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                try:
                    n = int(self.headers.get("Content-Length", "0") or 0)
                    msg = json.loads(self.rfile.read(n) or b"{}")
                    member = str(msg.get("member", ""))
                    if not member and path in ("/join", "/heartbeat",
                                               "/leave"):
                        raise ValueError("missing 'member'")
                    if path == "/join":
                        self._reply(svc.join(
                            member, host=str(msg.get("host", "?")),
                            pid=int(msg.get("pid", 0)),
                            rank=int(msg.get("rank", -1)),
                            generation=msg.get("generation"),
                            standby=bool(msg.get("standby", False)),
                            telemetry_addr=msg.get("telemetry"),
                            role=str(msg.get("role", "train"))))
                    elif path == "/heartbeat":
                        self._reply(svc.heartbeat(
                            member, generation=msg.get("generation"),
                            progress=msg.get("progress"),
                            steps=msg.get("steps")))
                    elif path == "/leave":
                        self._reply(svc.leave(
                            member, why=str(msg.get("why", "leave"))))
                    elif path == "/advance":
                        self._reply(svc.advance(
                            int(msg.get("generation", 0)),
                            why=str(msg.get("why", "relaunch"))))
                    else:
                        self.send_error(404)
                except (ValueError, TypeError, json.JSONDecodeError) as exc:
                    self._reply({"ok": False, "error": str(exc)}, code=400)

            def log_message(self, *args):
                pass

        srv = ThreadingHTTPServer((addr, self.port), _Handler)
        srv.daemon_threads = True
        self.port = srv.server_address[1]
        self._srv = srv
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mxtpu-coordinator-http").start()
        if _tm.enabled():
            _TM_GEN.set(self.generation)
            _TM_ALIVE.set(0)

        def _monitor():
            interval = max(self.lease_s / 4.0, 0.05)
            while not self._stop.wait(interval):
                try:
                    self.expire_leases()
                    self.eval_straggler()
                except Exception:  # noqa: BLE001 — monitor must survive
                    _logger.exception("coordinator lease monitor failed")

        self._monitor = threading.Thread(target=_monitor, daemon=True,
                                         name="mxtpu-coordinator-leases")
        self._monitor.start()
        self.scraper.start()
        _logger.info("coordinator serving on %s:%d (lease %.1fs, fleet "
                     "scrape every %.1fs)", addr, self.port, self.lease_s,
                     self.scraper.interval_s)
        return self

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}" if self._srv is not None else ""

    def stop(self):
        self._stop.set()
        self.scraper.stop()
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None


def _http_json(addr, path, payload=None, timeout=5.0):
    """One JSON RPC to the coordinator with a bounded socket timeout —
    a dead coordinator must surface as an error, never a hang."""
    import http.client

    host, port = str(addr).rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        if payload is None:
            conn.request("GET", path)
        else:
            body = json.dumps(payload).encode()
            conn.request("POST", path, body,
                         {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise MXNetError(f"coordinator {addr}{path}: HTTP "
                             f"{resp.status}: {data[:200]!r}")
        return json.loads(data)
    finally:
        conn.close()


class CoordinatorClient:
    """Worker-side membership: join + background heartbeats + the
    step-loop poll.  One per process; built by
    :func:`client_from_env` when the elastic launcher armed
    ``MXTPU_COORD_ADDR``."""

    _MISS_LIMIT = 5  # consecutive heartbeat failures = coordinator lost

    def __init__(self, addr, member=None, rank=None, generation=None,
                 standby=False, telemetry_addr=None, role="train"):
        from . import dist as _dist

        self.addr = str(addr)
        self.rank = _dist._rank_or_env() if rank is None else int(rank)
        self.role = str(role or "train")
        self.member = member or f"rank{self.rank}:{socket.gethostname()}" \
                                f":{os.getpid()}"
        self.generation = (_dist.generation() if generation is None
                           else int(generation))
        self.lease_s = coord_lease_s()
        # advertised /metrics endpoint for the fleet federation scrape
        # (default: the import-time MXTPU_TELEMETRY_HTTP_PORT server)
        self.telemetry_addr = (telemetry_addr if telemetry_addr is not None
                               else _tm.http_address())
        self._changed_at = None       # monotonic time a bump was seen
        self._seen_generation = self.generation
        self._polls = 0               # batches polled this incarnation
        self._lost = False            # coordinator unreachable
        self._misses = 0
        self._polled = False          # loop is actively polling
        self._last_poll = time.monotonic()
        self._stop = threading.Event()
        self._hb = None
        reply = self._rpc("/join", {"member": self.member,
                                    "host": socket.gethostname(),
                                    "pid": os.getpid(), "rank": self.rank,
                                    "generation": self.generation,
                                    "standby": bool(standby),
                                    "role": self.role,
                                    "telemetry": self.telemetry_addr})
        self.lease_s = float(reply.get("lease_s", self.lease_s))
        self._observe_generation(int(reply["generation"]))
        if not standby:
            self._hb = threading.Thread(target=self._heartbeat_loop,
                                        daemon=True,
                                        name="mxtpu-coord-heartbeat")
            self._hb.start()

    def _rpc(self, path, payload=None):
        try:
            return _http_json(self.addr, path, payload,
                              timeout=max(self.lease_s, 2.0))
        except (OSError, MXNetError, ValueError) as exc:
            raise HostLostError(
                "coordinator", host=self.addr, rank=self.rank,
                generation=self.generation,
                dump=_tm.health.auto_dump("fault"),
                detail=f"coordinator RPC {path} failed: {exc!r}") from exc

    def _observe_generation(self, gen):
        if gen != self._seen_generation:
            self._seen_generation = gen
            if self._changed_at is None:
                self._changed_at = time.monotonic()

    # -- background heartbeats + wedge watchdog -----------------------------
    def _heartbeat_loop(self):
        from .. import faults as _faults

        interval = max(self.lease_s / 3.0, 0.05)
        while not self._stop.wait(interval):
            try:
                if _faults.should_drop("coord_heartbeat"):
                    continue  # simulated lost heartbeat: lease decays
                # step-timing feed (ISSUE 14): per-step wall/dispatch
                # means from the flight ring — pure host-side reads, so
                # the straggler signal costs the hot loop nothing
                t_send = time.time()
                reply = _http_json(self.addr, "/heartbeat",
                                   {"member": self.member,
                                    "generation": self.generation,
                                    "progress": self._polls,
                                    "steps":
                                        _tm.health.step_time_stats()},
                                   timeout=max(interval, 2.0))
                t_recv = time.time()
                self._misses = 0
                self._observe_generation(int(reply["generation"]))
                server_time = reply.get("server_time")
                if server_time is not None:
                    # clock-offset estimate via the RTT midpoint: the
                    # common timebase fleetstat merge-trace aligns on
                    _tm.health.set_clock_offset(
                        float(server_time) - (t_send + t_recv) / 2.0,
                        rtt_s=t_recv - t_send)
            except Exception:  # noqa: BLE001 — counted, surfaced at poll
                self._misses += 1
                if self._misses >= self._MISS_LIMIT:
                    self._lost = True
            # wedge watchdog: a membership change was published but the
            # training loop never reached its next poll — it is parked
            # inside a dead collective.  Past the barrier timeout the
            # only way out jax leaves us is a named exit; the last
            # periodic checkpoint (PR 11) is the resume point.
            if (self._changed_at is not None and self._polled
                    and not self._stop.is_set()):
                wedged_s = time.monotonic() - max(self._changed_at,
                                                  self._last_poll)
                timeout = barrier_timeout_s()
                if timeout > 0 and wedged_s > timeout:
                    dump = _tm.health.auto_dump("fault")
                    _logger.error(
                        "generation %d -> %d published %.1fs ago and the "
                        "step loop never surfaced (wedged collective); "
                        "exiting %d for the elastic launcher%s",
                        self.generation, self._seen_generation, wedged_s,
                        EXIT_HOST_LOST,
                        f" (flight record: {dump})" if dump else "")
                    os._exit(EXIT_HOST_LOST)

    # -- loop-facing API ----------------------------------------------------
    def changed(self) -> bool:
        """True once the coordinator published a different generation
        (host death or rejoin) — the loop must leave at this boundary."""
        return self._changed_at is not None

    def step_poll(self) -> bool:
        """Per-batch poll from the training loops: pure host-side flag
        reads (never touches the device).  Fires the ``host_crash``
        chaos site, surfaces a lost coordinator as a named error, and
        returns :meth:`changed`."""
        from .. import faults as _faults

        _faults.maybe_fail("host_crash")
        self._polled = True
        self._polls += 1
        self._last_poll = time.monotonic()
        if self._lost:
            raise HostLostError(
                "coordinator", host=self.addr, rank=self.rank,
                generation=self.generation,
                dump=_tm.health.auto_dump("fault"),
                detail=f"{self._MISS_LIMIT} consecutive heartbeats failed")
        return self.changed()

    def raise_generation_changed(self, ckpt_path=None):
        """Build + raise the named boundary error (the fit loops call
        this AFTER their boundary checkpoint landed)."""
        raise GenerationChanged(
            "membership", host=self.addr, rank=self.rank,
            generation=self._seen_generation,
            dump=_tm.health.auto_dump("fault"),
            detail="cluster generation "
                   f"{self.generation} -> {self._seen_generation}"
                   + (f"; checkpoint: {ckpt_path}" if ckpt_path else
                      "; resume from the latest checkpoint"))

    def cluster(self):
        return self._rpc("/cluster")

    def leave(self, why="leave"):
        self._stop.set()
        try:
            self._rpc("/leave", {"member": self.member, "why": why})
        except HostLostError:
            pass  # leaving a dead coordinator is still leaving

    def stop(self):
        self._stop.set()
        if self._hb is not None:
            self._hb.join(timeout=2.0)


_default_client = None
_default_lock = threading.Lock()


def client_from_env():
    """The process-wide client when ``MXTPU_COORD_ADDR`` is armed (the
    elastic launcher sets it), else None.  Built once; the fit loops
    call this per run, not per batch."""
    global _default_client
    addr = coord_addr()
    if not addr:
        return None
    with _default_lock:
        if _default_client is None or _default_client.addr != addr:
            _default_client = CoordinatorClient(addr)
        return _default_client


def maybe_start_from_env(generation=None):
    """Rank 0 hosts the membership endpoint when ``MXTPU_COORD_PORT``
    is set (the non-launcher bring-up mode of docs/multihost.md);
    returns the service or None."""
    from . import dist as _dist

    port = os.environ.get("MXTPU_COORD_PORT", "").strip()
    if not port or _dist._rank_or_env() != 0:
        return None
    svc = CoordinatorService(
        port=int(port),
        generation=_dist.generation() if generation is None else generation)
    return svc.start()


def _main(argv=None):
    """Standalone coordinator: ``python -m mxnet_tpu.parallel.coordinator
    --port P [--lease S]`` — the elastic launcher runs this as its
    failure-detector subprocess."""
    import argparse

    ap = argparse.ArgumentParser(description="mxnet_tpu cluster coordinator")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--lease", type=float, default=None)
    ap.add_argument("--generation", type=int, default=0)
    args = ap.parse_args(argv)
    svc = CoordinatorService(port=args.port, lease_s=args.lease,
                             generation=args.generation).start()
    print(f"coordinator ready on {svc.address}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    _main()
