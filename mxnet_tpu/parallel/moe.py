"""Expert parallelism — mixture-of-experts FFN with all_to_all dispatch.

Absent from the reference (SURVEY §2.4 lists expert parallelism as a
gap); on TPU it is a first-class strategy: experts live on an 'expert'
mesh axis, tokens are routed by a learned gate, and two
`jax.lax.all_to_all` collectives carry each token to its expert's device
and back — the standard Switch-Transformer layout over ICI.

Design (top-k routing, dense dispatch; k=1 = Switch, k=2 = the
GShard/Mixtral configuration):
- tokens are sharded over the 'expert' axis ([tokens/world, d_model] per
  device),
- gate logits pick each token's top-k experts; tokens scatter into a
  [n_experts, capacity, d_model] buffer with capacity slots claimed
  choice-major — rank-0 picks never lose a slot to a runner-up — and
  over-capacity choices drop, like Switch,
- all_to_all swaps the expert axis with the device axis so each device
  holds ITS expert's tokens from every peer, runs the expert FFN as one
  batched matmul (MXU-friendly), and the inverse all_to_all + combine
  weights scatter results home.

Everything is differentiable: gates get gradients through the combine
weights, experts through their matmuls.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .mesh import shard_map


def init_moe_params(rng, d_model, d_hidden, n_experts, scale=0.02):
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "gate_w": jax.random.normal(k1, (d_model, n_experts)) * scale,
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_hidden)) * scale,
        "w_out": jax.random.normal(k3, (n_experts, d_hidden, d_model)) * scale,
    }


def moe_ffn(params, x, mesh: Mesh, axis_name: str = "expert",
            capacity_factor: float = 1.25, activation=jax.nn.relu,
            top_k: int = 1):
    """Apply the expert-parallel FFN.

    x: [tokens, d_model] sharded over `axis_name` on dim 0.
    params: gate_w [d, E]; w_in [E, d, h] / w_out [E, h, d] sharded over
    `axis_name` on dim 0 (one expert slice per device; E == axis size).
    top_k: experts per token — 1 = Switch routing, 2 = the GShard/
    Mixtral configuration (each choice gets its own capacity slot; the
    outputs combine weighted by the renormalized gate probabilities).
    Returns (y [tokens, d_model], aux_loss) — aux_loss is the
    load-balancing loss, to be added to the task loss.
    """
    n_exp = mesh.shape[axis_name]
    if not 1 <= top_k <= n_exp:
        raise ValueError(f"top_k must be in [1, {n_exp}], got {top_k}")

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, None), P(axis_name, None, None),
                       P(axis_name, None, None), P(axis_name, None)),
             out_specs=(P(axis_name, None), P()),
             check_rep=False)
    def run(gate_w, w_in, w_out, xs):
        nt = xs.shape[0]  # local tokens
        cap = max(1, int(capacity_factor * top_k * nt / n_exp))
        logits = xs @ gate_w                      # [nt, E]
        probs = jax.nn.softmax(logits, axis=-1)
        top_p, top_e = jax.lax.top_k(probs, top_k)  # [nt, k]
        if top_k == 1:
            gates = top_p  # Switch: the raw gate prob scales the output
        else:
            # GShard/Mixtral: renormalize over the chosen experts
            gates = top_p / jnp.maximum(
                jnp.sum(top_p, axis=-1, keepdims=True), 1e-9)

        # capacity slots are claimed choice-major (all rank-0 choices,
        # then rank-1, ...) so top-1 picks never lose a slot to a
        # runner-up choice; bookkeeping stays integer — in xs.dtype
        # (bf16) a cumsum over >256 same-expert tokens loses exactness
        # and two tokens silently share a slot
        disp = jnp.zeros((nt, n_exp, cap), xs.dtype)
        combine = jnp.zeros((nt, n_exp, cap), xs.dtype)
        counts = jnp.zeros((n_exp,), jnp.int32)
        for j in range(top_k):
            e_j = top_e[:, j]                                # [nt]
            onehot_i = jax.nn.one_hot(e_j, n_exp, dtype=jnp.int32)
            pos = (jnp.take_along_axis(
                jnp.cumsum(onehot_i, axis=0) - onehot_i,
                e_j[:, None], axis=1)[:, 0] + counts[e_j])
            keep = (pos < cap).astype(xs.dtype)
            sel = (jax.nn.one_hot(e_j, n_exp, dtype=xs.dtype)[:, :, None]
                   * jax.nn.one_hot(pos, cap, dtype=xs.dtype)[:, None, :]
                   * keep[:, None, None])
            disp = disp + sel
            combine = combine + sel * gates[:, j][:, None, None]
            counts = counts + jnp.sum(onehot_i, axis=0)
        buf = jnp.einsum("tec,td->ecd", disp, xs)  # [E, cap, d]

        # expert axis <-> device axis: after this, dim 0 indexes the PEER
        # device the tokens came from, and every row belongs to MY expert
        buf = jax.lax.all_to_all(buf, axis_name, 0, 0, tiled=False)
        # buf: [world, cap, d] for my expert
        w1, w2 = w_in[0], w_out[0]
        h = activation(jnp.einsum("wcd,dh->wch", buf, w1))
        y = jnp.einsum("wch,hd->wcd", h, w2)
        y = jax.lax.all_to_all(y, axis_name, 0, 0, tiled=False)  # home again

        # combine: weight by renormalized gate prob, scatter to tokens
        out = jnp.einsum("tec,ecd->td", combine, y)

        # load-balancing loss: E * sum_e f_e * P_e over rank-0 routing
        onehot0 = jax.nn.one_hot(top_e[:, 0], n_exp, dtype=jnp.float32)
        frac = jnp.mean(onehot0, axis=0)          # fraction routed per expert
        prob_mean = jnp.mean(probs.astype(jnp.float32), axis=0)
        aux = n_exp * jnp.sum(frac * prob_mean)
        aux = jax.lax.pmean(aux, axis_name)
        return out, aux.astype(xs.dtype)

    return run(params["gate_w"], params["w_in"], params["w_out"], x)
