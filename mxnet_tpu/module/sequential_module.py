"""SequentialModule — chain modules, feeding outputs to inputs.

Parity: python/mxnet/module/sequential_module.py (reference).
"""
from __future__ import annotations

import logging

from .. import ndarray as nd
from ..base import MXNetError
from ..io import DataBatch
from .base_module import BaseModule


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self._meta_keys = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}

    def add(self, module, **kwargs):
        self._modules.append(module)
        for key in kwargs:
            assert key in self._meta_keys, f"unknown meta {key}"
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        if self._modules:
            return self._modules[0].data_names
        return []

    @property
    def output_names(self):
        if self._modules:
            return self._modules[-1].output_names
        return []

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return (arg_params, aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params, allow_missing=True,
                               force_init=force_init)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert shared_module is None
        assert len(self._modules) > 0
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i_layer, (meta, module) in enumerate(zip(self._metas, self._modules)):
            meta_take_labels = meta.get(self.META_TAKE_LABELS, False)
            my_label_shapes = label_shapes if meta_take_labels else None
            if meta_take_labels:
                anybody_ever_needs_label = True
            my_inputs_need_grad = for_training and (inputs_need_grad or i_layer > 0)
            module.bind(data_shapes=my_data_shapes, label_shapes=my_label_shapes,
                        for_training=for_training,
                        inputs_need_grad=my_inputs_need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            if i_layer < len(self._modules) - 1:
                out = module._symbol
                my_data_shapes = [
                    (name, shape)
                    for name, shape in zip(
                        self._modules[i_layer + 1].data_names,
                        [s for _, s in module.output_shapes],
                    )
                ]
        if not anybody_ever_needs_label:
            self._label_shapes = None

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        assert self.binded and self.params_initialized
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = DataBatch(data=data_batch.data, label=data_batch.label,
                          pad=data_batch.pad)
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i < len(self._modules) - 1:
                batch = DataBatch(data=module.get_outputs(),
                                  label=data_batch.label, pad=data_batch.pad)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i in range(len(self._modules) - 1, -1, -1):
            module = self._modules[i]
            module.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._modules:
            module.install_monitor(mon)
