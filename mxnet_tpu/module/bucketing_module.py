"""BucketingModule — variable-length training with per-bucket executors.

Parity: python/mxnet/module/bucketing_module.py (reference:16;
switch_bucket:207-217).  The reference shares one memory pool across bucket
executors (GraphExecutor::Init(shared_exec) -> InitDataEntryMemory);
TPU-natively each bucket is a jit cache entry keyed by shape — the
``shared_module`` plumbing shares the compiled-function cache and params,
and XLA reuses device buffers across calls (SURVEY.md §5.7 bucketing row).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names, label_names, logger=self.logger,
                      context=self._context, work_load_list=self._work_load_list,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        assert shared_module is None, "shared_module not supported for BucketingModule"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Parity: bucketing_module.py:207 — bind new bucket with
        shared_module=default bucket (compile-cache + param sharing)."""
        assert self.binded, "call bind before switching buckets"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self._curr_module.inputs_need_grad,
                        force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key])
            if self.optimizer_initialized:
                # buckets created after init_optimizer share its state
                # (parity: switch_bucket borrow_optimizer,
                # bucketing_module.py:214-216)
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def get_params(self):
        assert self.binded and self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer, arg_params, aux_params,
                                      allow_missing, force_init)
        self.params_initialized = True
        self._params_dirty = False

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, "bucket_key", None)
        if bucket_key is None:
            bucket_key = self._default_bucket_key
        data_shapes = data_batch.provide_data or [
            (n, a.shape) for n, a in zip(self._curr_module.data_names, data_batch.data)
        ]
        label_shapes = data_batch.provide_label
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        # propagate latest params into the bucket's executor
        if self._curr_module.params_initialized is False:
            self._curr_module.params_initialized = True
        self._curr_module._exec_group.set_params(
            self._buckets[self._default_bucket_key]._arg_params or {},
            self._buckets[self._default_bucket_key]._aux_params or {})
        self._curr_module._arg_params = self._buckets[self._default_bucket_key]._arg_params
        self._curr_module._aux_params = self._buckets[self._default_bucket_key]._aux_params
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)
        self._params_dirty = True

    def update(self):
        self._curr_module.update()
        # write updated params back to the default bucket's master copy
        self._curr_module._sync_params_from_devices()
        self._params_dirty = False

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    @property
    def _params_dirty(self):
        return getattr(self, "_params_dirty_flag", False)

    @_params_dirty.setter
    def _params_dirty(self, val):
        self._params_dirty_flag = val
