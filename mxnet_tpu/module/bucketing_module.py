"""BucketingModule — variable-length training with per-bucket executors.

Parity: python/mxnet/module/bucketing_module.py (reference:16;
switch_bucket:207-217).  The reference shares one memory pool across bucket
executors (GraphExecutor::Init(shared_exec) -> InitDataEntryMemory);
TPU-natively each bucket is a jit cache entry keyed by shape — the
``shared_module`` plumbing shares the compiled-function cache and params,
and XLA reuses device buffers across calls (SURVEY.md §5.7 bucketing row).

Compile-cost control (SURVEY.md §7 "Bucketing vs compile cost"): on TPU a
new bucket = a new unrolled graph = a full XLA compile, so naive bucketing
pays seconds per bucket where the reference pays only a cheap memory-plan
reuse.  ``compile_buckets`` caps that: bucket keys are rounded UP to a
small set of compile keys, batches are padded along the bucketed axis to
the compile key's shape, and the padded positions carry ``label_pad`` so a
symbol built with ``use_ignore=True, ignore_label=label_pad`` gets *exactly*
the same gradients as the unpadded bucket graph (SoftmaxOutput masks both
loss and d(loss) at ignored labels — ops/loss.py).  With
``compile_buckets=True`` everything runs through the default bucket's one
executable: ≤2 XLA compilations (fwd, fused fwd+bwd) for any number of
buckets.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc
from ..ndarray import NDArray
from .base_module import BaseModule
from .module import Module


def _key_tuple(key):
    return tuple(key) if isinstance(key, (list, tuple)) else (key,)


def _key_le(a, b):
    ta, tb = _key_tuple(a), _key_tuple(b)
    return len(ta) == len(tb) and all(x <= y for x, y in zip(ta, tb))


def _pad_shape(shape, default_shape, key, default_key, ckey):
    """Compute the padded target shape for one array.

    The bucketed axes are exactly those where this batch's shape differs
    from the default bucket's bound shape (so constant axes — batch size,
    hidden dims — are never touched even if they numerically collide with a
    bucket key).  Each such axis maps to the bucket-key component whose
    value matches it, and is promoted to that component of the compile key.
    """
    if default_shape is None or len(default_shape) != len(shape):
        return tuple(shape)
    tk = _key_tuple(key)
    tdk = _key_tuple(default_key)
    tck = _key_tuple(ckey)
    out = []
    for d, dd in zip(shape, default_shape):
        if d != dd:
            for j, kc in enumerate(tk):
                if d == kc and (j >= len(tdk) or tdk[j] == dd):
                    d = tck[j]
                    break
        out.append(d)
    return tuple(out)


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 compile_buckets=None, data_pad=0.0, label_pad=0.0):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        if compile_buckets is True:
            compile_buckets = [default_bucket_key]
        if compile_buckets:
            compile_buckets = list(compile_buckets)
            if not any(_key_le(default_bucket_key, k) for k in compile_buckets):
                compile_buckets.append(default_bucket_key)
            compile_buckets.sort(key=_key_tuple)
        self._compile_buckets = compile_buckets or None
        self._data_pad = data_pad
        self._label_pad = label_pad
        self._metric_labels = None  # padded labels for update_metric

    def _compile_key(self, bucket_key):
        """Smallest compile bucket covering bucket_key (identity when off)."""
        if not self._compile_buckets:
            return bucket_key
        for ck in self._compile_buckets:
            if _key_le(bucket_key, ck):
                return ck
        raise MXNetError(
            f"bucket_key {bucket_key!r} exceeds every compile bucket "
            f"{self._compile_buckets!r}")

    def _pad_batch(self, data_batch, key, ckey):
        """Pad a bucket-``key`` batch up to the compile bucket's shapes.

        Data pads with ``data_pad``; labels pad with ``label_pad`` so that a
        use_ignore symbol contributes zero loss/gradient at the padding."""
        default_mod = self._buckets[self._default_bucket_key]
        defaults = dict(default_mod._data_shapes)
        if default_mod._label_shapes:
            defaults.update(dict(default_mod._label_shapes))

        def pad(arrs, descs, names, fill):
            import jax.numpy as jnp

            out_arrs, out_descs = [], []
            for i, a in enumerate(arrs):
                shape = tuple(a.shape)
                name = descs[i][0] if descs and i < len(descs) else names[i]
                tgt = _pad_shape(shape, defaults.get(name), key,
                                 self._default_bucket_key, ckey)
                if tgt != shape:
                    if isinstance(a, NDArray):
                        # pad on whatever device the array lives — no
                        # host round-trip for device-staged pipelines
                        raw = a._read()
                    else:
                        raw = jnp.asarray(np.asarray(a))
                    widths = [(0, t - s) for s, t in zip(shape, tgt)]
                    a = NDArray(jnp.pad(raw, widths, constant_values=fill))
                out_arrs.append(a)
                out_descs.append(DataDesc(name, tgt))
            return out_arrs, out_descs

        mod = self._curr_module
        data, ddesc = pad(data_batch.data, data_batch.provide_data or [],
                          mod.data_names, self._data_pad)
        if data_batch.label is not None:
            label, ldesc = pad(data_batch.label, data_batch.provide_label or [],
                               mod._label_names, self._label_pad)
        else:
            label, ldesc = None, None
        return DataBatch(data, label=label, pad=data_batch.pad,
                         index=data_batch.index, bucket_key=ckey,
                         provide_data=ddesc, provide_label=ldesc)

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names, label_names, logger=self.logger,
                      context=self._context, work_load_list=self._work_load_list,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        assert shared_module is None, "shared_module not supported for BucketingModule"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Parity: bucketing_module.py:207 — bind new bucket with
        shared_module=default bucket (compile-cache + param sharing)."""
        assert self.binded, "call bind before switching buckets"
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self._curr_module.inputs_need_grad,
                        force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key])
            if self.optimizer_initialized:
                # buckets created after init_optimizer share its state
                # (parity: switch_bucket borrow_optimizer,
                # bucketing_module.py:214-216)
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def get_params(self):
        assert self.binded and self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._curr_module.init_params(initializer, arg_params, aux_params,
                                      allow_missing, force_init)
        self.params_initialized = True
        self._params_dirty = False

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, "bucket_key", None)
        if bucket_key is None:
            bucket_key = self._default_bucket_key
        compile_key = self._compile_key(bucket_key)
        self._orig_labels = data_batch.label
        if compile_key != bucket_key:
            data_batch = self._pad_batch(data_batch, bucket_key, compile_key)
            bucket_key = compile_key
        self._metric_labels = data_batch.label
        data_shapes = data_batch.provide_data or [
            (n, a.shape) for n, a in zip(self._curr_module.data_names, data_batch.data)
        ]
        label_shapes = data_batch.provide_label
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        default_mod = self._buckets[self._default_bucket_key]
        if self._curr_module.params_initialized is False:
            self._curr_module.params_initialized = True
        # propagate latest params into the bucket's executor — but only
        # when this bucket does NOT live-share param storage with the
        # default bucket (executor_group same-mesh sharing): shared chunks
        # already see every optimizer write, and re-pushing the master
        # copy was a full param-set device_put on every batch
        if (self._curr_module is not default_mod
                and not getattr(self._curr_module._exec_group,
                                "shares_param_storage", False)):
            self._curr_module._exec_group.set_params(
                default_mod._arg_params or {},
                default_mod._aux_params or {})
        self._curr_module._arg_params = default_mod._arg_params
        self._curr_module._aux_params = default_mod._aux_params
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)
        self._params_dirty = True

    def update(self):
        self._curr_module.update()
        # write updated params back to the default bucket's master copy
        self._curr_module._sync_params_from_devices()
        self._params_dirty = False

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        # Under compile-bucket padding the executor outputs carry the
        # padded length, so the labels the caller took from the ORIGINAL
        # batch no longer line up — substitute the padded labels (the
        # ignore_label masks the padding).  Only the fit()-style case
        # where the caller passes that same batch's labels is rewritten;
        # custom label lists pass through untouched.
        if (self._compile_buckets and self._metric_labels is not None
                and labels is not None
                and getattr(self, "_orig_labels", None) is not None
                and len(labels) == len(self._orig_labels)
                and all(a is b for a, b in zip(labels, self._orig_labels))):
            labels = self._metric_labels
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    @property
    def _params_dirty(self):
        return getattr(self, "_params_dirty_flag", False)

    @_params_dirty.setter
    def _params_dirty(self, val):
        self._params_dirty_flag = val
