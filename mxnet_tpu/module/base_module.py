"""BaseModule — the high-level train/predict interface.

Parity: python/mxnet/module/base_module.py (reference).  fit (:315) is the
canonical training loop of SURVEY.md §3.1: forward_backward -> update ->
update_metric, with epoch/batch callbacks, eval data, and checkpointing.
"""
from __future__ import annotations

import logging
import os
import time
from collections import namedtuple

import numpy as np

from .. import engine as _engine
from .. import metric as metric_mod
from .. import ndarray as nd
from .. import telemetry as _tm
from ..base import MXNetError

# same family the fused path uses (trainer.py); loop label tells them apart
_TM_SAMPLES = _tm.counter(
    "trainer_samples_total", "training samples dispatched",
    labels=("loop",))

BatchEndParam = namedtuple("BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _as_list(obj):
    if obj is None:
        return []
    return obj if isinstance(obj, list) else [obj]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------------- high level
    def forward_backward(self, data_batch):
        """Parity: base_module.py:140."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def _output_handles(self):
        """Raw device arrays of the last step's outputs — what the
        bounded async window blocks on.  Modules whose outputs are not
        device arrays return [] (the window then never stalls on them)."""
        try:
            outs = self.get_outputs()
        except Exception:  # noqa: BLE001 — e.g. PythonModule variants
            return []
        handles = []
        for o in outs:
            read = getattr(o, "_read", None)
            if read is not None:
                handles.append(read())
        return handles

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None,
              score_end_callback=None, reset=True, epoch=0):
        """Parity: base_module.py score — run eval_data through the net."""
        assert self.binded and self.params_initialized
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        nbatch = 0
        # bounded in-flight window: with fused metrics nothing in this
        # loop reads device values, so the window is what keeps the host
        # from racing arbitrarily far ahead of the device
        window = _engine.AsyncWindow()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            window.push(self._output_handles())
            if batch_end_callback is not None:
                params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                       eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(params)
        window.drain()
        if score_end_callback is not None:
            params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                   eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(params)
        # global view survives any auto_reset batch callback (see fit)
        return eval_metric.get_global_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0 : out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True, reset=True,
                always_output_list=False):
        """Parity: base_module.py predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [
                nd.array(out.asnumpy()[0 : out.shape[0] - pad]) for out in self.get_outputs()
            ]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [
                nd.array(np.concatenate([out[i].asnumpy() for out in output_list], axis=0))
                for i in range(num_outputs)
            ]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, checkpoint=None, resume=None):
        """Parity: BaseModule.fit (base_module.py:315).

        Survival layer (docs/fault_tolerance.md): ``checkpoint`` is a
        CheckpointManager or directory (default: armed by
        ``MXTPU_CKPT_DIR`` + ``MXTPU_CKPT_EVERY``); ``resume=True`` (or
        a path) restores the newest complete checkpoint — params, aux,
        optimizer state, RNG, and the epoch/batch cursor — before
        training, and a SIGTERM saves a boundary checkpoint then raises
        :class:`mxnet_tpu.checkpoint.Preempted`."""
        assert num_epoch is not None, "please specify number of epochs"
        from .. import checkpoint as _ckpt
        from ..initializer import Uniform

        initializer = initializer or Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if isinstance(checkpoint, _ckpt.CheckpointManager):
            mgr = checkpoint
        elif checkpoint:
            mgr = _ckpt.CheckpointManager(str(checkpoint))
        else:
            mgr = _ckpt.CheckpointManager.from_env()
        if mgr is not None and not hasattr(self, "_checkpoint_arrays"):
            self.logger.warning(
                "checkpointing requested but %s has no checkpoint "
                "provider; disabled", type(self).__name__)
            mgr = None
        resume_nbatch, resume_step = -1, 0
        if resume not in (None, False):
            if mgr is None and not checkpoint:
                raise MXNetError("fit(resume=...) needs MXTPU_CKPT_DIR "
                                 "(or a checkpoint= manager/directory)")
            if not hasattr(self, "_restore_checkpoint"):
                raise MXNetError(f"{type(self).__name__} has no "
                                 "checkpoint provider; resume is "
                                 "unsupported")
            path = (resume if isinstance(resume, str)
                    and os.path.exists(os.path.join(resume, _ckpt.MANIFEST))
                    else _ckpt.resolve_resume(resume, mgr))
            if path is None:
                self.logger.warning("fit(resume=%r): no complete "
                                    "checkpoint found; starting fresh",
                                    resume)
            else:
                arrays, manifest = _ckpt.load(path)
                meta = self._restore_checkpoint(arrays, manifest)
                if meta.get("epoch") is not None:
                    begin_epoch = int(meta["epoch"])
                if meta.get("nbatch") is not None:
                    resume_nbatch = int(meta["nbatch"])
                resume_step = int(meta.get("step") or 0)
                if _tm.enabled():
                    _ckpt._TM_RESUME.inc(status="ok")
                self.logger.info(
                    "resumed from %s (step %d, epoch %d, batch cursor "
                    "%d)", path, resume_step, begin_epoch, resume_nbatch)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        if mgr is not None:
            mgr.install_preempt_handler()
        try:
            final_step = self._fit_epochs(
                train_data, eval_data, eval_metric,
                validation_metric, begin_epoch, num_epoch,
                epoch_end_callback, batch_end_callback,
                eval_end_callback, eval_batch_end_callback,
                monitor, mgr, resume_nbatch, resume_step)
            if mgr is not None:
                # terminal checkpoint: resuming a finished run is a
                # no-op instead of a silent full retrain
                self._save_checkpoint_state(mgr, final_step, num_epoch,
                                            -1, background=False)
        except BaseException:
            # black box first, then crash: dump the flight record (ring
            # + registry + memory report) when MXTPU_FLIGHT_RECORD
            # names a path, then let the exception propagate
            _tm.health.auto_dump("exception")
            raise
        finally:
            if mgr is not None:
                try:
                    mgr.wait()
                except Exception as exc:  # noqa: BLE001 — log, not mask
                    self.logger.warning("checkpoint writer failed: %r",
                                        exc)
                mgr.uninstall_preempt_handler()

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, begin_epoch, num_epoch,
                    epoch_end_callback, batch_end_callback,
                    eval_end_callback, eval_batch_end_callback, monitor,
                    mgr=None, resume_nbatch=-1, start_step=0):
        from .. import checkpoint as _ckpt
        from ..parallel import coordinator as _coordinator

        # elastic membership (docs/multihost.md): armed by
        # MXTPU_COORD_ADDR; step_poll is a pure host-side flag check
        coord = _coordinator.client_from_env()
        flight = _tm.health.flight_enabled()
        perf_on = _tm.perf.enabled()
        rec = flight or perf_on
        program = None
        if flight:
            try:
                program = getattr(self._exec_group.execs[0],
                                  "_program_label", None)
            except Exception:  # noqa: BLE001 — PythonModule variants
                pass
        step_id = start_step
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            # bounded in-flight window (MXTPU_ASYNC_DEPTH, default 2):
            # fused metrics make update_metric a pure enqueue, so the
            # steady-state loop below performs no per-batch device sync —
            # the host only blocks here when the window fills, and at the
            # epoch boundary where values are genuinely needed
            window = _engine.AsyncWindow()
            prev_tick = None  # per-epoch: wall_s must not span eval/reset
            for nbatch, data_batch in enumerate(train_data):
                if epoch == begin_epoch and nbatch <= resume_nbatch:
                    # mid-epoch resume: the checkpoint's cursor already
                    # trained these batches — replay the iterator past
                    # them so the step/schedule sequence lines up
                    continue
                if monitor is not None:
                    monitor.tic()
                step_id += 1
                t0 = time.perf_counter() if rec else 0.0
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                tp = time.perf_counter() if perf_on else 0.0
                window.push(self._output_handles())
                if rec:
                    # step-timing feed (ISSUE 14): wall_s is the full
                    # batch-to-batch host wall — what the coordinator
                    # heartbeat reports for straggler detection.  Pure
                    # perf_counter reads, no device sync.
                    now = time.perf_counter()
                    if flight:
                        _tm.health.record_step(
                            loop="module", step=step_id, epoch=epoch,
                            nbatch=nbatch, depth=len(window),
                            dispatch_s=now - t0,
                            wall_s=(now - prev_tick
                                    if prev_tick is not None else now - t0),
                            program=program)
                    if perf_on:
                        # step decomposition (docs/perf_attr.md): the
                        # buckets partition the batch-to-batch wall by
                        # construction — same stamps the flight feed
                        # takes, zero device syncs
                        _tm.perf.record_step_buckets(
                            wall_s=(now - prev_tick
                                    if prev_tick is not None else now - t0),
                            data_wait=(max(t0 - prev_tick, 0.0)
                                       if prev_tick is not None else 0.0),
                            dispatch=tp - t0,
                            window_stall=now - tp)
                    prev_tick = now
                if coord is not None and coord.step_poll():
                    # the cluster generation moved (a host died or a
                    # rejoiner announced): checkpoint this boundary,
                    # then leave with the named error — the elastic
                    # launcher relaunches the new generation, which
                    # re-binds on the new mesh shape via resume
                    w = None
                    if mgr is not None:
                        w = self._save_checkpoint_state(
                            mgr, step_id, epoch, nbatch, background=False)
                    coord.raise_generation_changed(
                        getattr(w, "path", None))
                if mgr is not None:
                    if mgr.preempted:
                        w = self._save_checkpoint_state(
                            mgr, step_id, epoch, nbatch,
                            background=False)
                        raise _ckpt.Preempted(
                            "SIGTERM: checkpoint saved to "
                            f"{getattr(w, 'path', mgr.directory)!r}; "
                            "restart with fit(resume=True)")
                    if mgr.due(step_id):
                        self._save_checkpoint_state(mgr, step_id, epoch,
                                                    nbatch)
                if _tm.enabled() and data_batch.data:
                    _TM_SAMPLES.inc(
                        data_batch.data[0].shape[0]
                        - (data_batch.pad or 0), loop="module")
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric, locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
            # epoch boundary: the checkpoint/eval callbacks below need the
            # device caught up, and the epoch log reads the metric values
            td0 = time.perf_counter() if perf_on else 0.0
            window.drain()
            if perf_on:
                _tm.perf.record_bucket("boundary_sync",
                                       time.perf_counter() - td0)
            # global view: correct even when a Speedometer(auto_reset=True)
            # batch callback reset the metric's local window mid-epoch
            for name, val in eval_metric.get_global_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)

            # the reference re-broadcasts get_params() through set_params
            # here to reconcile per-device aux divergence (BN stats) —
            # with ONE mesh-global executor there is nothing to
            # reconcile, and the round-trip re-uploaded every param+aux
            # each epoch; get_params alone syncs the host copies the
            # callbacks consume
            arg_params_, aux_params_ = self.get_params()
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params_, aux_params_)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)
            train_data.reset()
        return step_id

    def _save_checkpoint_state(self, mgr, step, epoch, nbatch,
                               background=True):
        """One survival-layer snapshot through the module's checkpoint
        provider (:meth:`_checkpoint_arrays`): device-resident arrays
        only — capture dispatches async copies, the writer thread does
        the fetch + IO, and the training loop never blocks."""
        from .. import random as _random

        arrays, extra = self._checkpoint_arrays()
        key = np.asarray(_random.current_key())
        meta = {"module": type(self).__name__, "step": int(step),
                "epoch": int(epoch), "nbatch": int(nbatch),
                # sync-ok: checkpoint cadence only (mgr.due/preempt), never
                # per-batch; the tiny RNG key was fetched by np.asarray
                # above and must serialize into the manifest
                "rng_key": key.tolist(), "rng_dtype": str(key.dtype)}
        sig = getattr(self._symbol, "structural_signature", None)
        if callable(sig):
            meta["signature"] = sig()
        meta.update(extra)
        return mgr.save(step, arrays, meta=meta, background=background)

    # -------------------------------------------------------- to be overridden
    @property
    def symbol(self):
        return self._symbol

    def get_params(self):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False, force_init=True):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def install_monitor(self, mon):
        raise NotImplementedError
