"""DataParallelExecutorGroup — data parallelism over a device mesh.

Parity: python/mxnet/module/executor_group.py (reference:66): the reference
slices each batch across contexts (_split_input_slice), binds one executor
per device, scatters inputs (_load_data:41) and gathers outputs
(_merge_multi_context:50); gradients meet in the kvstore.

TPU-native redesign (SURVEY.md §7 'Data parallelism' row): ONE executor,
ONE compiled SPMD program.  The contexts resolve onto the process-level
named 2-D mesh ``("batch", "model")`` (parallel.mesh.global_mesh,
MXTPU_MESH_SHAPE; a context subset gets a batch-only sub-mesh): input
batches are bound with a ``NamedSharding(P("batch"))`` annotation
threaded through ``simple_bind(shardings=...)``, params/grads are
replicated (group2ctx PartitionSpec annotations may shard them over
"model").  XLA GSPMD inserts the gradient all-reduce over ICI — the
engine-scheduled P2P copy + ElementwiseSum machinery of CommDevice
(src/kvstore/comm.h:200-360) becomes a single fused collective, counted
per step in ``executor_collective_bytes_total{op=grad_allreduce}``.
The slice/merge API surface is preserved so Module code is unchanged.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import ndarray as nd
from .. import telemetry as _tm
from ..base import MXNetError
from ..executor import _TM_COLLECTIVE, simple_bind
from ..ndarray import NDArray
from ..parallel.mesh import GLOBAL_AXES, create_mesh, global_mesh


def _split_input_slice(batch_size, work_load_list):
    """Parity: executor_manager.py:15 — kept for API compat (slices are
    virtual on TPU; sharding does the real split)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for w in work_load_list:
        end = start + int(round(batch_size * w / total))
        slices.append(slice(start, min(end, batch_size)))
        start = end
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write"):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])

        self.data_names = [d[0] for d in data_shapes]
        self.label_names = [l[0] for l in label_shapes] if label_shapes else []
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self.batch_size = data_shapes[0][1][0]

        # ----- named 2-D mesh (the TPU-native executor "group") -------------
        # contexts spanning every process device use the process-level
        # ("batch", "model") mesh — MXTPU_MESH_SHAPE decides how many
        # replicas vs model shards; a context subset keeps a batch-only
        # sub-mesh over exactly those devices (reference parity: the
        # group computes on the contexts it was given, PlaceDevice-style)
        devices = [c.jax_device for c in contexts]
        unique = []
        for d in devices:
            if d not in unique:
                unique.append(d)
        if len(unique) == len(jax.devices()):
            mesh = global_mesh(unique)
        else:
            mesh = create_mesh((len(unique), 1), GLOBAL_AXES,
                               devices=unique)
        n_batch, n_model = mesh.devices.shape
        if self.batch_size % n_batch != 0:
            # GSPMD shards the batch evenly, so an uneven request uses the
            # LARGEST replica count dividing the batch — and says so (the
            # reference's _split_input_slice gave devices uneven slices;
            # silently dropping to one device is not acceptable either way)
            n = n_batch
            while self.batch_size % n:
                n -= 1
            import logging

            (logger or logging.getLogger()).warning(
                "batch size %d not divisible by %d devices; data-parallel "
                "group uses %d device(s) — pad the batch or adjust "
                "batch_size for full utilization",
                self.batch_size, n_batch, n)
            unique = unique[:n * n_model]
            mesh = create_mesh((n, n_model), GLOBAL_AXES, devices=unique)
        self.mesh = mesh
        self._data_sharding = NamedSharding(self.mesh, P("batch"))
        self._repl_sharding = NamedSharding(self.mesh, P())

        arg_names = symbol.list_arguments()
        self.arg_names = arg_names
        self.aux_names = symbol.list_auxiliary_states()

        input_shapes = dict([(n, s) for n, s in data_shapes] +
                            ([(n, s) for n, s in label_shapes] if label_shapes else []))
        req = {}
        for name in arg_names:
            if name in self.data_names:
                req[name] = "write" if inputs_need_grad else "null"
            elif name in self.label_names or name in self.fixed_param_names:
                req[name] = "null"
            else:
                req[name] = grad_req if for_training else "null"
        # shared_exec is the object-identity fast path (same Symbol =>
        # donor's jits); a regenerated bucket symbol misses it but still
        # reuses compiled programs through the executor's process-wide
        # program cache (structural signature), so switch_bucket never
        # recompiles a structure it has seen
        shared_exec = shared_group.execs[0] if shared_group is not None else None
        # the bind carries the mesh annotations: inputs batch-sharded,
        # everything else replicated (a group2ctx PartitionSpec via the
        # executor may override single params onto the "model" axis) —
        # ONE compiled SPMD program spans the mesh, and the sharding
        # spec joins the program-cache key alongside the structure hash
        shardings = None
        if self.mesh.size > 1:
            shardings = {}
            for name in self.data_names + self.label_names:
                if name in arg_names or name in input_shapes:
                    shardings[name] = self._data_sharding
            for name in arg_names:
                if name not in shardings:
                    shardings[name] = self._repl_sharding
            for name in self.aux_names:
                shardings.setdefault(name, self._repl_sharding)
        exec_ = simple_bind(symbol, contexts[0], grad_req=req,
                            shared_exec=shared_exec, shardings=shardings,
                            **input_shapes)
        same_mesh = (shared_group is not None
                     and list(shared_group.mesh.devices.flat)
                     == list(self.mesh.devices.flat))
        # set True below only when EVERY param this group holds live-shares
        # the donor's storage; BucketingModule consults it to skip the
        # per-forward master-param push (a partially-shared group — e.g. a
        # shape-mismatched param — must keep receiving pushes)
        self.shares_param_storage = False
        if shared_exec is not None and same_mesh:
            # LIVE param/aux sharing (reference parity: shared_module
            # executors share parameter storage, module.py:346-349 +
            # the shared memory pool — an update through EITHER module
            # is immediately visible to the other; bucketing and
            # train-then-serve sharing both rely on it).  Sharing the
            # NDArray object shares its chunk, so in-place optimizer
            # writes propagate.  Only when both groups run the SAME
            # device mesh: a sharee on a trimmed mesh (smaller batch)
            # would re-shard the donor's live chunks out from under its
            # compiled step — there, snapshot semantics remain.
            shared_all = True
            for name in self.param_names:
                donor = shared_exec.arg_dict.get(name)
                mine = exec_.arg_dict.get(name)
                if donor is not None and mine is not None \
                        and donor.shape == mine.shape:
                    exec_.arg_dict[name] = donor
                elif mine is not None:
                    shared_all = False
            exec_.arg_arrays = [exec_.arg_dict[n] for n in arg_names]
            for name, donor in shared_exec.aux_dict.items():
                mine = exec_.aux_dict.get(name)
                if mine is not None and donor.shape == mine.shape:
                    exec_.aux_dict[name] = donor
                elif mine is not None:
                    shared_all = False
            exec_.aux_arrays = [exec_.aux_dict[n] for n in self.aux_names]
            self.shares_param_storage = shared_all
        # replicate params over the mesh so GSPMD sees them as shared
        if len(unique) > 1:
            for name, arr in exec_.arg_dict.items():
                if name not in self.data_names and name not in self.label_names:
                    arr._chunk.write(jax.device_put(arr._read(), self._repl_sharding))
            for arr in exec_.aux_dict.values():
                arr._chunk.write(jax.device_put(arr._read(), self._repl_sharding))
        self.execs = [exec_]
        self.slices = _split_input_slice(self.batch_size, self.workload)
        # logical payload of the per-step gradient all-reduce GSPMD
        # inserts for replicated params over a >1-replica mesh (counted
        # at backward dispatch into executor_collective_bytes_total)
        self._grad_allreduce_bytes = 0
        if self.mesh.devices.shape[0] > 1:
            # row-sparse grads are not dense-all-reduced (their rows
            # segment-sum inside the sparse bucket program) — counting
            # the full table here would overstate the collective payload
            self._grad_allreduce_bytes = sum(
                int(g.size) * np.dtype(g.dtype).itemsize
                for n, g in exec_.grad_dict.items()
                if g is not None and n not in self.data_names
                and getattr(g, "stype", "default") == "default")

    # ---------------------------------------------------------------- params
    def set_params(self, arg_params, aux_params):
        ex = self.execs[0]
        for name, arr in arg_params.items():
            if name in ex.arg_dict:
                ex.arg_dict[name]._chunk.write(self._replicate(arr))
        for name, arr in (aux_params or {}).items():
            if name in ex.aux_dict:
                ex.aux_dict[name]._chunk.write(self._replicate(arr))

    def _replicate(self, arr):
        # ALWAYS place on the group's devices: params handed in are host
        # arrays (Module master copies), and writing them through as-is
        # would leave executor buffers on the wrong platform when the
        # default device is an accelerator (caught by lstm_bucketing on a
        # real TPU host: cpu weight vs tpu grad in the optimizer).
        raw = arr._read() if isinstance(arr, NDArray) else jax.numpy.asarray(arr)
        return jax.device_put(raw, self._repl_sharding)

    def get_params(self, arg_params, aux_params):
        ex = self.execs[0]
        for name in self.param_names:
            if name in ex.arg_dict:
                arg_params[name] = ex.arg_dict[name].copy()
        for name, arr in ex.aux_dict.items():
            aux_params[name] = arr.copy()

    # --------------------------------------------------------------- running
    def forward(self, data_batch, is_train=None):
        """Parity: executor_group forward — scatter + forward.  Scatter is a
        sharded device_put (one ICI-free host->device transfer per shard)."""
        if is_train is None:
            is_train = self.for_training
        ex = self.execs[0]
        self._load(ex, self.data_names, data_batch.data)
        if self.label_names and data_batch.label:
            self._load(ex, self.label_names, data_batch.label)
        ex.forward(is_train=is_train)

    def _load(self, ex, names, arrays):
        for name, arr in zip(names, arrays):
            raw = arr._read() if isinstance(arr, NDArray) else jax.numpy.asarray(np.asarray(arr))
            # always place on the group's devices (host-resident batches
            # would otherwise leave the input on the cpu platform when the
            # executor runs on an accelerator)
            raw = jax.device_put(raw, self._data_sharding)
            # bypass _set's device pinning: sharded placement is intentional
            ex.arg_dict[name]._chunk.write(raw)

    def backward(self, out_grads=None):
        self.execs[0].backward(out_grads)
        if self._grad_allreduce_bytes and _tm.enabled():
            _TM_COLLECTIVE.inc(self._grad_allreduce_bytes,
                               op="grad_allreduce")

    def get_outputs(self, merge_multi_context=True):
        """Outputs are global (sharded) arrays — 'merge' is free."""
        return list(self.execs[0].outputs)

    def get_output_handles(self):
        """Raw jax arrays of the current step's outputs — the handles
        the fit/score async window blocks on.  Reading them materializes
        a pending lazy forward as a DISPATCH (no host sync): the arrays
        stay futures until someone blocks on them."""
        return [o._read() for o in self.execs[0].outputs]

    def get_input_grads(self, merge_multi_context=True):
        ex = self.execs[0]
        return [ex.grad_dict[n] for n in self.data_names if n in ex.grad_dict]

    @property
    def grad_arrays(self):
        """Per-param grad lists (length-1: the mesh-global grad) — parity
        shape [[grad_per_device]] collapses to [[global_grad]]."""
        ex = self.execs[0]
        return [[ex.grad_dict[n]] for n in self.param_names if n in ex.grad_dict]

    @property
    def param_arrays(self):
        ex = self.execs[0]
        return [[ex.arg_dict[n]] for n in self.param_names if n in ex.arg_dict]

    def get_update_data(self):
        """(key indices, per-key grad lists, per-key weight arrays) for
        the module's BATCHED kvstore step: one ``push(keys, grads)`` +
        ``pull(keys, outs)`` call per step instead of one per key, which
        the kvstore routes to the bucketed jit-fused update engine when
        eligible.  Indices match ``init_optimizer``'s enumeration of
        ``param_names`` (keys the kvstore was initialized with)."""
        ex = self.execs[0]
        idxs, grads, weights = [], [], []
        for idx, name in enumerate(self.param_names):
            g = ex.grad_dict.get(name)
            if g is None:
                continue
            idxs.append(idx)
            grads.append([g])
            weights.append(ex.arg_dict[name])
        return idxs, grads, weights

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, mon):
        for ex in self.execs:
            mon.install(ex)
