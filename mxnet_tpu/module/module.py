"""Module — the modern training API over one symbol.

Parity: python/mxnet/module/module.py (reference:21; bind:276,
init_optimizer:379, update:489).  Data parallelism is delegated to the
mesh-based DataParallelExecutorGroup; the kvstore update path preserves the
reference's two modes (_create_kvstore, model.py:40-77):

- update_on_kvstore=True: push(grad) then pull(weight) per key; optimizer
  runs inside the store,
- update_on_kvstore=False: store aggregates only (push/pull grad); the
  module runs the Updater locally.
"""
from __future__ import annotations

import logging


from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from ..context import cpu, Context
from ..model import _create_kvstore, load_checkpoint, save_checkpoint
from ..ndarray import NDArray
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None):
        super().__init__(logger=logger)
        if context is None:
            from ..context import default_accelerator_context

            context = [default_accelerator_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list or [1] * len(context)

        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._fixed_param_names = list(fixed_param_names or [])
        self._exec_group = None
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Parity: Module.load — from save_checkpoint files."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Parity: Module.save_checkpoint."""
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, self._arg_params, self._aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    # ---------------------------------------------------------------- binding
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        # infer from the bound input shapes — must work before any forward
        # (SequentialModule.bind chains on it while wiring sub-modules)
        shapes = {}
        for d in list(self._data_shapes) + list(self._label_shapes or []):
            name, shape = (d.name, d.shape) if hasattr(d, "name") else (d[0], d[1])
            shapes[name] = shape
        _, out_shapes, _ = self._symbol.infer_shape_partial(**shapes)
        return list(zip(self.output_names, out_shapes))

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Parity: Module.bind (module.py:276)."""
        if force_rebind:
            if self.binded and self.params_initialized:
                # pull the trained values out of the executors BEFORE
                # discarding them — the push below would otherwise hand
                # the fresh executors stale init-time _arg_params
                self._sync_params_from_devices()
            self._exec_group = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = [tuple(x) for x in data_shapes]
        self._label_shapes = [tuple(x) for x in label_shapes] if label_shapes else None

        shared_group = shared_module._exec_group if shared_module is not None else None
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, self._data_shapes,
            self._label_shapes or [], self._param_names, for_training,
            inputs_need_grad, shared_group=shared_group, logger=self.logger,
            fixed_param_names=self._fixed_param_names, grad_req=grad_req)
        if shared_module is not None and shared_module.params_initialized:
            self.set_params(*shared_module.get_params())
        elif self.params_initialized and self._arg_params is not None:
            # re-bind (or Module.load -> bind): the fresh executors must
            # receive the parameters this module already holds — the
            # reference's bind pushes them the same way (module.py:276)
            self._exec_group.set_params(self._arg_params,
                                        self._aux_params or {})

    # ----------------------------------------------------------------- params
    def get_params(self):
        assert self.binded and self.params_initialized
        self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def _sync_params_from_devices(self):
        if self._params_dirty and self._exec_group is not None:
            self._arg_params = self._arg_params or {}
            self._aux_params = self._aux_params or {}
            self._exec_group.get_params(self._arg_params, self._aux_params)
            self._params_dirty = False

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        """Parity: Module.init_params."""
        if self.params_initialized and not force_init:
            if arg_params or aux_params:
                self._set_params_direct(arg_params, aux_params, allow_missing)
            return
        assert self.binded, "call bind before init_params"
        from ..initializer import Uniform

        initializer = initializer if initializer is not None else Uniform(0.01)

        ex = self._exec_group.execs[0]
        # per-variable init= attrs override the global initializer
        # (parity: the reference's InitDesc/__init__ attr protocol)
        from .. import initializer as _init_mod

        var_inits = {}
        for node in self._symbol.nodes:
            if node.is_variable and node.extra_attrs.get("__init__"):
                try:
                    var_inits[node.name] = _init_mod.create(
                        node.extra_attrs["__init__"])
                except MXNetError:
                    pass
        self._arg_params = {}
        self._aux_params = {}
        for name in self._param_names:
            if name not in ex.arg_dict:
                continue
            arr = nd.zeros(ex.arg_dict[name].shape)
            if arg_params is not None and name in arg_params:
                arr[:] = arg_params[name].asnumpy()
            else:
                if arg_params is not None and not allow_missing and arg_params:
                    raise MXNetError(f"param {name} missing")
                init_fn = var_inits.get(name, initializer)
                if init_fn is not None:
                    init_fn(name, arr)
            self._arg_params[name] = arr
        for name in self._aux_names:
            arr = nd.zeros(ex.aux_dict[name].shape)
            if aux_params is not None and name in aux_params:
                arr[:] = aux_params[name].asnumpy()
            else:
                if initializer is not None:
                    initializer(name, arr)
            self._aux_params[name] = arr
        self._exec_group.set_params(self._arg_params, self._aux_params)
        self.params_initialized = True
        self._params_dirty = False

    def _set_params_direct(self, arg_params, aux_params, allow_missing=False):
        for k, v in (arg_params or {}).items():
            if k in self._arg_params:
                self._arg_params[k][:] = v.asnumpy()
        for k, v in (aux_params or {}).items():
            if k in self._aux_params:
                self._aux_params[k][:] = v.asnumpy()
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False, force_init=True):
        if not self.params_initialized:
            self.params_initialized = True
            self._arg_params = {k: v.copy() for k, v in (arg_params or {}).items()}
            self._aux_params = {k: v.copy() for k, v in (aux_params or {}).items()}
            self._exec_group.set_params(self._arg_params, self._aux_params)
            return
        self._set_params_direct(arg_params, aux_params, allow_missing)

    # -------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        """Parity: Module.init_optimizer (module.py:379)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized")
            return
        kvstore_inst, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._arg_params)

        batch_size = self._exec_group.batch_size
        if (kvstore_inst and "dist" in kvstore_inst.type
                and "_sync" in kvstore_inst.type
                and not kvstore_inst.collective):
            # PS sync mode: every worker contributes its OWN batch and
            # the server sums, so the effective batch is B * workers.
            # Collective mode feeds ONE mesh-global batch shared by all
            # hosts (GSPMD shards it) — B already IS the global batch.
            batch_size *= kvstore_inst.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = dict(enumerate(self._param_names))
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        self._optimizer = optimizer
        self._kvstore = kvstore_inst
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore_inst:
            # parity: _initialize_kvstore (model.py) — init each param slot,
            # then PULL the stored value back: multi-worker launches init
            # with different local random params, and only rank 0's init
            # defines the shared model — every worker must start from it
            ex = self._exec_group.execs[0]
            # the pull-back only matters when other workers exist (their
            # random init differs); single-process stores would round-trip
            # the value just pushed
            pull_back = update_on_kvstore and kvstore_inst.num_workers > 1
            for idx, name in enumerate(self._param_names):
                if name in self._arg_params:
                    init_val = self._arg_params[name]
                    grad = ex.grad_dict.get(name)
                    if getattr(grad, "stype", "default") == "row_sparse":
                        # the param's gradient arrives row-sparse, so
                        # its key must be initialized row-sparse or the
                        # stype check would (rightly) reject the push
                        from .. import sparse as _sparse

                        init_val = _sparse.full_row_sparse(init_val)
                    kvstore_inst.init(idx, init_val)
                    if pull_back:
                        kvstore_inst.pull(idx, ex.arg_dict[name],
                                          priority=-idx)
                        self._arg_params[name][:] = ex.arg_dict[name].asnumpy()
            if update_on_kvstore:
                kvstore_inst.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """Parity: Module.borrow_optimizer — share optimizer state across
        bucket modules."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    # ------------------------------------------------------------ computation
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads)

    def update(self):
        """Parity: Module.update (module.py:489) + model.py:88-118.

        Non-dist stores take the batched path — ONE ``push(keys, grads)``
        + ``pull(keys, outs)`` per step, which the kvstore routes to the
        bucketed jit-fused update engine (kvstore_fused.py) when the
        optimizer qualifies.  PS-transport dist stores keep the per-key
        loop: their comm/compute overlap rides per-key engine priorities
        (SURVEY §3.4), which a single batched RPC would flatten.
        COLLECTIVE dist_sync (no PS servers — ISSUE 13) batches like a
        local store: the cross-host all-reduce is already inside the
        compiled step/bucket programs, so per-key RPC priorities have
        nothing left to overlap."""
        assert self.binded and self.params_initialized and self.optimizer_initialized
        self._params_dirty = True
        ex = self._exec_group.execs[0]
        dist = (self._kvstore is not None and "dist" in self._kvstore.type
                and not self._kvstore.collective)
        if self._kvstore is not None and not dist:
            idxs, grads, weights = self._exec_group.get_update_data()
            self._kvstore.push(idxs, grads)
            if self._update_on_kvstore:
                self._kvstore.pull(idxs, weights)
            else:
                # aggregation-only store: pull merged grads back, then
                # run the local updater (eager per-key — the fallback
                # contract for custom updaters)
                self._kvstore.pull(idxs, [g[0] for g in grads])
                for idx, name in zip(
                        idxs, (n for n in self._param_names
                               if n in ex.grad_dict)):
                    self._updater(idx, ex.grad_dict[name],
                                  ex.arg_dict[name])
            return
        if self._update_on_kvstore:
            for idx, name in enumerate(self._param_names):
                if name not in ex.grad_dict:
                    continue
                # push grad; optimizer runs in-store; pull weight back
                self._kvstore.push(idx, [ex.grad_dict[name]], priority=-idx)
                self._kvstore.pull(idx, ex.arg_dict[name], priority=-idx)
        else:
            for idx, name in enumerate(self._param_names):
                if name not in ex.grad_dict:
                    continue
                grad = ex.grad_dict[name]
                if self._kvstore:
                    self._kvstore.push(idx, [grad], priority=-idx)
                    self._kvstore.pull(idx, grad, priority=-idx)
                self._updater(idx, grad, ex.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def _output_handles(self):
        return self._exec_group.get_output_handles()

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        self._exec_group.install_monitor(mon)

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    # ---------------------------------------------------- survival layer
    def _active_updater(self):
        if self._update_on_kvstore and self._kvstore is not None:
            return getattr(self._kvstore, "_updater", None)
        return self._updater

    def _checkpoint_arrays(self):
        """Checkpoint provider (docs/fault_tolerance.md): the device-
        resident arrays of this module's training state — exec-group
        params/aux, per-key optimizer-state slots, and (when the fused
        engine shards) the flat sharded state vectors captured AS-IS
        from the device (no ``sync_shard_state`` gather on the hot
        loop; the shard layout rides in the meta so restore can decode
        into per-key form and re-shard through the engine's fingerprint
        re-ingest).  Returns ``(arrays, extra_meta)``."""
        assert self.binded and self.params_initialized
        from .. import amp as _amp
        from ..kvstore_fused import _state_slots

        ex = self._exec_group.execs[0]
        arrs = {}
        for name in self._param_names:
            if name in ex.arg_dict:
                arrs["param/" + name] = ex.arg_dict[name]._read()
        for name in self._aux_names:
            if name in ex.aux_dict:
                arrs["aux/" + name] = ex.aux_dict[name]._read()
        extra = {}
        kv = self._kvstore
        dist = kv is not None and "dist" in kv.type
        sharded_keys = set()
        shard_meta = {}
        fused = getattr(kv, "_fused", None) if (kv is not None
                                                and not dist) else None
        if fused is not None:
            for bi, b in enumerate(fused._buckets or ()):
                if b.shard_state is None:
                    continue
                for s, f in enumerate(b.shard_state):
                    arrs[f"optflat/{bi}/{s}"] = f
                shard_meta[str(bi)] = {
                    "keys": list(b.keys),
                    "offsets": [int(o) for o in b.offsets],
                    "sizes": [int(s_) for s_ in b.sizes],
                    "shapes": [list(sh) for sh in b.shapes],
                    "slots": len(b.shard_state),
                    "mp": bool(b.mp),
                }
                sharded_keys.update(b.keys)
        if shard_meta:
            extra["optflat"] = shard_meta
        upd = None if dist else self._active_updater()
        if upd is not None:
            for key, st in upd.states.items():
                if key in sharded_keys or st is None:
                    continue
                for j, leaf in enumerate(_state_slots(st)):
                    arrs[f"opt/{key}/{j}"] = leaf._read()
        if self._optimizer is not None:
            extra["opt_counts"] = {
                str(k): int(v) for k, v in getattr(
                    self._optimizer, "_index_update_count", {}).items()}
            extra["num_update"] = int(getattr(self._optimizer,
                                              "num_update", 0))
        if _amp.scaling_active():
            sc = _amp.global_scaler()
            arrs["amp/scale"] = sc._scale
            arrs["amp/good"] = sc._good
            arrs["amp/overflows"] = sc._overflows
            arrs["amp/skipped"] = sc._skipped
        if dist:
            extra["dist_note"] = ("dist store: optimizer state lives "
                                  "server-side; weights only")
        return arrs, extra

    @staticmethod
    def _ckpt_key(raw):
        """JSON round-trips int kvstore keys as strings in some meta
        positions; normalize back."""
        if isinstance(raw, str) and raw.lstrip("-").isdigit():
            return int(raw)
        return raw

    def _restore_checkpoint(self, arrays, manifest):
        """Restore a survival-layer checkpoint into this bound+
        initialized module: exec-group params/aux, the kvstore's
        canonical weight copies, per-key optimizer state (sharded flat
        vectors decoded through the saved layout; the fused engine's
        (chunk, version) fingerprints then re-ingest them into the
        CURRENT shard layout on the next step — restore re-shards),
        optimizer step counters, the loss-scale scalar, and the RNG
        stream.  Returns the checkpoint's meta dict."""
        import jax.numpy as jnp

        from .. import amp as _amp
        from .. import checkpoint as _ckpt
        from .. import random as _random
        from ..kvstore_fused import _state_slots

        assert self.binded and self.params_initialized
        meta = manifest.get("meta", {})
        sig = getattr(self._symbol, "structural_signature", None)
        saved_sig = meta.get("signature")
        if callable(sig) and saved_sig is not None and saved_sig != sig():
            raise _ckpt.CheckpointError(
                "checkpoint was saved from a different graph (signature "
                f"{saved_sig[:16]}... vs bound {sig()[:16]}...); "
                "refusing to load mismatched weights")
        missing = [n for n in self._param_names
                   if "param/" + n not in arrays]
        if missing:
            raise _ckpt.CheckpointError(
                f"checkpoint lacks params {missing[:5]}...")
        missing_aux = [n for n in self._aux_names
                       if "aux/" + n not in arrays]
        if missing_aux:
            raise _ckpt.CheckpointError(
                f"checkpoint lacks aux states {missing_aux[:5]}...")
        arg_params = {n: nd.array(arrays["param/" + n])
                      for n in self._param_names}
        aux_params = {n: nd.array(arrays["aux/" + n])
                      for n in self._aux_names}
        self.set_params(arg_params, aux_params, force_init=True)
        kv = self._kvstore
        dist = kv is not None and "dist" in kv.type
        ex = self._exec_group.execs[0]
        if kv is not None and not dist:
            # the store's canonical weight copies feed the next update;
            # leaving them stale would undo the restore on step 1
            for idx, name in enumerate(self._param_names):
                if idx in kv._store:
                    kv._store[idx]._set(
                        jnp.asarray(arrays["param/" + name]))
        upd = None if dist else self._active_updater()
        if upd is not None:
            def _weight_for(key):
                if kv is not None and key in kv._store:
                    return kv._store[key]
                if isinstance(key, int) and key < len(self._param_names):
                    return ex.arg_dict.get(self._param_names[key])
                return ex.arg_dict.get(key)

            def _fill(key, slot_hosts):
                w = _weight_for(key)
                if w is None:
                    return
                leaves = _state_slots(upd.ensure_state(key, w))
                for j, host in slot_hosts:
                    if j >= len(leaves):
                        continue
                    leaf = leaves[j]
                    leaf._chunk.write(jnp.asarray(host).reshape(
                        leaf.shape).astype(leaf.dtype))

            per_key = {}
            for name, host in arrays.items():
                if not name.startswith("opt/"):
                    continue
                _, key, j = name.split("/", 2)
                per_key.setdefault(self._ckpt_key(key), []).append(
                    (int(j), host))
            for key, slot_hosts in per_key.items():
                _fill(key, sorted(slot_hosts))
            for bi, bm in (meta.get("optflat") or {}).items():
                flats = [arrays[f"optflat/{bi}/{s}"]
                         for s in range(int(bm["slots"]))]
                for i, key in enumerate(bm["keys"]):
                    key = self._ckpt_key(key)
                    off = int(bm["offsets"][i])
                    size = int(bm["sizes"][i])
                    shape = tuple(bm["shapes"][i])
                    _fill(key, [(s, flats[s][off:off + size]
                                 .reshape(shape))
                                for s in range(len(flats))])
            # the fused engine's shard_src fingerprints now disagree
            # with the rewritten per-key chunks: the next sharded step
            # re-ingests them into the CURRENT mesh layout
        if self._optimizer is not None:
            counts = meta.get("opt_counts") or {}
            self._optimizer._index_update_count = {
                self._ckpt_key(k): int(v) for k, v in counts.items()}
            if meta.get("num_update") is not None:
                self._optimizer.num_update = int(meta["num_update"])
        if "amp/scale" in arrays and _amp.scaling_active():
            sc = _amp.global_scaler()
            with sc._lock:
                sc._scale = jnp.asarray(arrays["amp/scale"])
                sc._good = jnp.asarray(arrays["amp/good"])
                sc._overflows = jnp.asarray(arrays["amp/overflows"])
                sc._skipped = jnp.asarray(arrays["amp/skipped"])
        if meta.get("rng_key") is not None:
            import numpy as _np

            _random._state["key"] = jnp.asarray(_np.array(
                meta["rng_key"],
                dtype=_np.dtype(meta.get("rng_dtype", "uint32"))))
        self._params_dirty = False
        return meta
