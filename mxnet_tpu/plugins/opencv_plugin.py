"""OpenCV plugin (parity: plugin/opencv — imdecode / resize /
copyMakeBorder NDArray functions plus the python augment helpers in
plugin/opencv/opencv.py).

Like the reference plugin, the kernels call real libopencv (cv2) when
it is importable: imdecode, resize and copyMakeBorder go straight to
cv2 with the reference's flag values (which match cv2's numerically).
Without cv2 the same API rides the framework's own decode path (native
libjpeg in src/jpeg_decode.cc when built, PIL otherwise —
mxnet_tpu/image.py) and numpy/PIL for geometry; results agree within
interpolation tolerance (pinned by tests/test_plugins.py).

ONE deliberate deviation from cv2 either way: channel order is **RGB**
(matching the rest of mxnet_tpu's image pipeline), not BGR — ported
scripts must flip any BGR-ordered mean/std constants.
"""
from __future__ import annotations

import numpy as np

from .. import image as _image
from ..base import MXNetError
from ..ndarray import NDArray, array

try:  # real OpenCV when present — the reference plugin's backend
    import cv2 as _cv2
except ImportError:  # pragma: no cover - depends on image
    _cv2 = None

# cv2 flag parity
INTER_NEAREST = 0
INTER_LINEAR = 1
INTER_CUBIC = 2
BORDER_CONSTANT = 0
BORDER_REPLICATE = 1

_PIL_INTERP = {INTER_NEAREST: 0, INTER_LINEAR: 2, INTER_CUBIC: 3}


def imdecode(str_img, flag=1):
    """Decode a jpeg/png byte string into an HWC uint8 NDArray.
    flag=1 color, flag=0 grayscale (cv2.imdecode convention)."""
    raw = bytes(str_img)
    if _cv2 is not None:
        buf = np.frombuffer(raw, np.uint8)
        mode = (_cv2.IMREAD_UNCHANGED if flag < 0
                else _cv2.IMREAD_COLOR if flag else _cv2.IMREAD_GRAYSCALE)
        img = _cv2.imdecode(buf, mode)
        if img is None:
            raise MXNetError("cv2.imdecode failed (corrupt stream?)")
        if img.ndim == 2:
            img = img[..., None]
        elif img.shape[-1] >= 3:  # RGB contract (alpha stays last)
            img = img[..., [2, 1, 0] + list(range(3, img.shape[-1]))]
        return array(np.ascontiguousarray(img))
    img = _image.imdecode_np(raw)  # HWC uint8 (native libjpeg or PIL)
    if flag == 0:
        # ITU-R BT.601 luma over RGB-ordered channels
        img = (img @ np.array([0.299, 0.587, 0.114]))[..., None]
        img = img.astype(np.uint8)
    return array(img)


def resize(src, size, interpolation=INTER_LINEAR):
    """Resize HWC image to `size` = (w, h) (cv2 size convention)."""
    data = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    if _cv2 is not None and data.dtype in (np.uint8, np.uint16,
                                           np.float32, np.float64):
        # flag values match cv2's numerically (INTER_* = 0/1/2); other
        # dtypes (int64 from np.asarray of ints, float16, ...) fall
        # through to the PIL plane path, which casts and restores
        out = _cv2.resize(data, tuple(size), interpolation=interpolation)
        if data.ndim == 3 and data.shape[-1] == 1:
            out = out[..., None]  # cv2 drops the singleton channel
        return array(np.ascontiguousarray(out))
    from PIL import Image
    interp = _PIL_INTERP.get(interpolation, 2)
    if data.dtype == np.uint8:
        squeeze = data.shape[-1] == 1
        pil = Image.fromarray(data.squeeze(-1) if squeeze else data)
        out = np.asarray(pil.resize(tuple(size), interp))
        if squeeze:
            out = out[..., None]
    else:
        # float input (e.g. color_normalize output, zero-centered): cv2
        # preserves dtype, so resize channel-wise as mode-'F' planes —
        # casting to uint8 here would truncate/wrap the values
        if data.ndim == 2:
            out = np.asarray(Image.fromarray(
                data.astype(np.float32), mode="F").resize(tuple(size), interp))
        else:
            planes = [
                np.asarray(Image.fromarray(
                    data[..., c].astype(np.float32), mode="F").resize(
                        tuple(size), interp))
                for c in range(data.shape[-1])
            ]
            out = np.stack(planes, axis=-1)
        out = out.astype(data.dtype)
    return array(out)


def copyMakeBorder(src, top, bot, left, right, border_type=BORDER_CONSTANT,
                   value=0):
    """Pad an HWC image (cv2.copyMakeBorder)."""
    data = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    if _cv2 is not None and border_type in (BORDER_CONSTANT,
                                            BORDER_REPLICATE):
        # flag values match cv2's numerically (BORDER_* = 0/1)
        val = value if isinstance(value, (tuple, list)) else [value] * 4
        out = _cv2.copyMakeBorder(data, top, bot, left, right, border_type,
                                  value=val)
        if data.ndim == 3 and data.shape[-1] == 1 and out.ndim == 2:
            out = out[..., None]
        return array(np.ascontiguousarray(out))
    pads = ((top, bot), (left, right), (0, 0))
    if border_type == BORDER_CONSTANT:
        out = np.pad(data, pads, constant_values=value)
    elif border_type == BORDER_REPLICATE:
        out = np.pad(data, pads, mode="edge")
    else:
        raise MXNetError(f"unsupported border_type {border_type}")
    return array(out)


def scale_down(src_size, size):
    """Parity: opencv.py scale_down — fit (w,h) inside src_size."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def fixed_crop(src, x0, y0, w, h, size=None, interpolation=INTER_CUBIC):
    """Crop [y0:y0+h, x0:x0+w], optionally resizing to `size`."""
    data = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = array(data[y0:y0 + h, x0:x0 + w])
    if size is not None and (w, h) != tuple(size):
        out = resize(out, size, interpolation)
    return out


def random_crop(src, size, rng=None):
    """Random crop to (w,h) (scaled down to fit), returns (img, (x0,y0,w,h))."""
    rng = rng or np.random
    h, w = (src.shape[0], src.shape[1])
    new_w, new_h = scale_down((w, h), size)
    x0 = int(rng.uniform(0, w - new_w + 1))
    y0 = int(rng.uniform(0, h - new_h + 1))
    out = fixed_crop(src, x0, y0, new_w, new_h, size)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(img - mean) / std in float32."""
    data = src.asnumpy().astype(np.float32)
    data -= np.asarray(mean, np.float32)
    if std is not None:
        data /= np.asarray(std, np.float32)
    return array(data)


def random_size_crop(src, size, min_area=0.25, ratio=(3.0 / 4.0, 4.0 / 3.0),
                     rng=None):
    """Inception-style area+aspect jittered crop; falls back to
    random_crop when no candidate fits (parity: opencv.py)."""
    rng = rng or np.random
    h, w = src.shape[0], src.shape[1]
    for _ in range(10):
        area = h * w
        target_area = rng.uniform(min_area, 1.0) * area
        aspect = rng.uniform(*ratio)
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if rng.uniform(0, 1) < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = int(rng.uniform(0, w - new_w + 1))
            y0 = int(rng.uniform(0, h - new_h + 1))
            return fixed_crop(src, x0, y0, new_w, new_h, size), \
                (x0, y0, new_w, new_h)
    return random_crop(src, size, rng)


class ImageListIter:
    """Minimal folder+list iterator (parity: opencv.py ImageListIter):
    decodes with this module, yields NCHW float batches."""

    def __init__(self, root, flist, batch_size, size, mean=None):
        self.root = root
        self.list = list(flist)
        self.batch_size = batch_size
        self.size = tuple(size)
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.cur = 0

    def reset(self):
        self.cur = 0

    def __iter__(self):
        return self

    def __next__(self):
        import os

        if self.cur + self.batch_size > len(self.list):
            raise StopIteration
        batch = np.zeros((self.batch_size, 3, self.size[1], self.size[0]),
                         np.float32)
        for i in range(self.batch_size):
            with open(os.path.join(self.root, self.list[self.cur + i]),
                      "rb") as f:
                img = imdecode(f.read())
            img, _ = random_crop(img, self.size)
            data = img.asnumpy().astype(np.float32)
            if self.mean is not None:
                data -= self.mean
            batch[i] = data.transpose(2, 0, 1)
        self.cur += self.batch_size
        return array(batch)

    next = __next__
