"""Optional plugins (parity: plugin/ in the reference — torch, caffe,
warpctc, opencv, sframe, compiled in via make flags).

Here each plugin is an importable module that registers extra ops when
its backing library is present:

- ``plugins.torch_plugin`` — TorchModule / TorchCriterion over CPU
  torch (parity: plugin/torch/).  Imported automatically when torch is
  installed.
- WarpCTC is a built-in op (ops/ctc.py) — no plugin needed.
- ``plugins.opencv_plugin`` — the plugin/opencv surface (imdecode,
  resize, copyMakeBorder, crop/normalize helpers, ImageListIter) backed
  by the framework's native/PIL decode instead of libopencv.
- Caffe / SFrame plugins have no backing libraries in this environment;
  importing them raises with a clear message (the reference gates them
  behind build flags the same way).
"""


def _try_torch():
    try:
        import torch  # noqa: F401
    except ImportError:
        return False
    from . import torch_plugin  # noqa: F401

    return True


torch_available = _try_torch()
