"""Torch interop ops (parity: plugin/torch/ — TorchModule/TorchCriterion,
which embedded Lua-torch layers inside MXNet graphs).

TPU-native design: the torch module runs on the host CPU behind
``jax.pure_callback`` with a custom VJP that calls torch autograd for the
backward — the XLA graph stays compiled around the host island, the
same escape-hatch architecture as the Custom op (ops/custom.py).  Torch
parameters are passed in as explicit graph inputs so they train under
any mxnet_tpu optimizer.

Usage::

    torch_mod = torch.nn.Linear(4, 3)
    out = nd.TorchModule(x, w, b, module_id=register_module(torch_mod))

or symbolically with variables for each torch parameter.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
import torch

from ..base import parse_attr
from ..ops.registry import register

_MODULES: dict[int, "torch.nn.Module"] = {}
_CRITERIA: dict[int, "torch.nn.Module"] = {}


def register_module(module) -> int:
    """Register a torch.nn.Module; returns the id to pass as module_id.
    The module's parameters (in ``module.parameters()`` order) become
    the op's trailing inputs."""
    mid = len(_MODULES)
    _MODULES[mid] = module.cpu()
    return mid


def register_criterion(criterion) -> int:
    mid = len(_CRITERIA)
    _CRITERIA[mid] = criterion.cpu()
    return mid


def _load_params(module, params):
    with torch.no_grad():
        for p, new in zip(module.parameters(), params):
            p.copy_(torch.from_numpy(np.asarray(new)))


def _run_forward(module, is_train, x, params, seed):
    was_training = module.training
    module.train(bool(is_train))
    try:
        _load_params(module, params)
        # the backward pass re-runs the forward: seeding both identically
        # makes stochastic layers (dropout) sample the same masks
        torch.manual_seed(int(seed))
        with torch.no_grad():
            return module(torch.from_numpy(np.asarray(x))).numpy()
    finally:
        module.train(was_training)


def _run_backward(module, is_train, x, params, gout, seed):
    was_training = module.training
    module.train(bool(is_train))
    # buffers (BatchNorm running stats...) were already advanced by the
    # forward pass — snapshot so the recompute doesn't advance them twice
    buffers = [b.detach().clone() for b in module.buffers()]
    try:
        _load_params(module, params)
        for p in module.parameters():
            p.requires_grad_(True)
            p.grad = None
        torch.manual_seed(int(seed))
        # torch.tensor copies: callback buffers are read-only numpy views
        xt = torch.tensor(np.asarray(x)).requires_grad_(True)
        out = module(xt)
        out.backward(torch.tensor(np.asarray(gout)))
        grads = [xt.grad.numpy() if xt.grad is not None
                 else np.zeros(xt.shape, np.float32)]
        grads += [p.grad.detach().numpy() if p.grad is not None
                  else np.zeros(tuple(p.shape), np.float32)
                  for p in module.parameters()]
        return tuple(grads)
    finally:
        with torch.no_grad():
            for b, saved in zip(module.buffers(), buffers):
                b.copy_(saved)
        module.train(was_training)


@register("TorchModule", arg_names=("data",), varargs=True)
def _torch_module(ctx, data, *params, **attrs):
    """Run a registered torch.nn.Module as a graph node (parity:
    plugin/torch/torch_module-inl.h).  Inputs: data + one array per
    torch parameter; attrs: module_id."""
    mid = int(parse_attr(attrs["module_id"]))
    module = _MODULES[mid]
    is_train = bool(ctx.is_train)  # static per traced executable

    # shape probe: eval mode (batch-1 through train-mode BatchNorm would
    # crash), buffers restored so the probe leaves no trace
    was_training = module.training
    buffers = [b.detach().clone() for b in module.buffers()]
    module.eval()
    try:
        with torch.no_grad():
            probe = module(torch.zeros((1,) + tuple(data.shape[1:])))
    finally:
        with torch.no_grad():
            for b, saved in zip(module.buffers(), buffers):
                b.copy_(saved)
        module.train(was_training)
    out_shape = (data.shape[0],) + tuple(probe.shape[1:])
    out_sds = jax.ShapeDtypeStruct(out_shape, jnp.float32)

    # one seed per invocation, shared by forward and backward-recompute
    # so stochastic layers sample identical masks
    # (carried as float32: custom_vjp wants float cotangents for its
    # differentiable positional args; the host side truncates back)
    if ctx._key is not None:
        seed = jax.random.randint(ctx.rng(), (), 0, 2**31 - 1).astype(jnp.float32)
    else:
        seed = jnp.float32(0)

    @jax.custom_vjp
    def apply(x, seed, *ps):
        return jax.pure_callback(
            lambda ss, xx, *pp: _run_forward(module, is_train, xx, pp, ss),
            out_sds, seed, x, *ps)

    def fwd(x, seed, *ps):
        return apply(x, seed, *ps), (x, seed, ps)

    def bwd(res, g):
        x, seed, ps = res
        shapes = tuple(jax.ShapeDtypeStruct(p.shape, jnp.float32) for p in (x,) + ps)
        grads = jax.pure_callback(
            lambda ss, xx, gg, *pp: _run_backward(module, is_train, xx, pp,
                                                  gg, ss),
            shapes, seed, x, g, *ps)
        return (grads[0], jnp.zeros_like(seed)) + tuple(grads[1:])

    apply.defvjp(fwd, bwd)
    return apply(data.astype(jnp.float32), seed,
                 *[p.astype(jnp.float32) for p in params])


@register("TorchCriterion", arg_names=("data", "label"))
def _torch_criterion(ctx, data, label, **attrs):
    """Torch loss as an output op (parity: plugin/torch/
    torch_criterion-inl.h).  Forward emits the per-call loss; backward
    feeds d(loss)/d(data) from torch autograd, ignoring head grads like
    the reference's loss layers."""
    mid = int(parse_attr(attrs["criterion_id"]))
    crit = _CRITERIA[mid]
    grad_scale = float(parse_attr(attrs.get("grad_scale", 1.0)))

    def fwd_host(x, y):
        xt = torch.from_numpy(np.asarray(x))
        yt = torch.from_numpy(np.asarray(y))
        loss = crit(xt, yt)
        if loss.numel() != 1:
            raise ValueError(
                "TorchCriterion requires a scalar loss — register the "
                "criterion with a reduction (e.g. reduction='mean'), got "
                f"output shape {tuple(loss.shape)}")
        return np.asarray(loss.item(), np.float32)

    def bwd_host(x, y):
        xt = torch.tensor(np.asarray(x)).requires_grad_(True)
        yt = torch.tensor(np.asarray(y))
        loss = crit(xt, yt)
        loss.backward()
        return xt.grad.numpy() * grad_scale

    @jax.custom_vjp
    def apply(x, y):
        return jax.pure_callback(fwd_host,
                                 jax.ShapeDtypeStruct((), jnp.float32), x, y)

    def fwd(x, y):
        return apply(x, y), (x, y)

    def bwd(res, g):
        x, y = res
        dx = jax.pure_callback(bwd_host,
                               jax.ShapeDtypeStruct(x.shape, jnp.float32),
                               x, y)
        return dx, jnp.zeros_like(y)

    apply.defvjp(fwd, bwd)
    return apply(data.astype(jnp.float32), label.astype(jnp.float32))


# late registration: regenerate the autogen op functions so
# nd.TorchModule / sym.TorchModule exist even though this plugin loads
# after the package (both init fns skip names that already exist)
from .. import ndarray as _nd  # noqa: E402
from .. import symbol as _sym  # noqa: E402

_nd._init_op_functions()
_sym._init_symbol_functions()
