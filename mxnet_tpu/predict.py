"""Predict-only inference API.

Parity: include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc
(reference): a self-contained ABI — ``MXPredCreate`` (symbol JSON +
param blob + input shapes), ``MXPredSetInput``, ``MXPredForward``,
``MXPredGetOutputShape``, ``MXPredGetOutput``, ``MXPredPartialForward``,
``MXPredFree`` — used by the amalgamation/mobile/JNI builds, with the
engine forced to the synchronous NaiveEngine (``MXNET_PREDICT_ONLY``,
include/mxnet/base.h:72-74).

TPU-native design: a Predictor is ONE jitted XLA computation (inputs →
outputs) with weights captured as device constants; ``forward`` is a
single dispatch.  The same class backs the C predict ABI exported from
src/ (see src/c_predict.cc) so non-Python frontends get the reference's
deployment story.
"""
from __future__ import annotations

import os

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError


class Predictor:
    """Parity: the ``MXPredCreate``/``SetInput``/``Forward``/``GetOutput``
    lifecycle rolled into one object.

    ``dtype``: inference compute precision.  ``"bfloat16"`` casts fp32
    weights/inputs to bf16 *inside* the compiled program (the casts fuse
    into the first consumers) and casts outputs back to fp32 — the
    deployment analog of ``FusedTrainer(dtype='bfloat16')``.  Default is
    the checkpoint's own precision; the ``MXTPU_PREDICT_DTYPE`` env var
    sets it for non-Python clients of the C ABI (src/c_predict.cc),
    which construct this class without kwargs.

    Graph passes: the bind below runs the training-safe rewrite
    pipeline (mxnet_tpu.passes) like every executor bind, and the
    constructor additionally applies inference-only Conv+BN folding —
    frozen BatchNorm moving stats and affine params are folded into the
    producing conv's weights/bias, removing a normalization per conv
    from every forward.  ``MXTPU_GRAPH_PASSES=0`` restores the
    unrewritten graph bit-identically.

    ``quantize="int8"``: post-training weight quantization
    (serving/quantize.py) — fp 2-D matmul and 4-D conv ``*weight``
    params are stored as int8 + per-channel symmetric scales and
    dequantized *inside* the compiled program, so the
    ``astype * scale`` fuses into each weight's consumer.  4x smaller
    weight residency than fp32 (composable with ``dtype="bfloat16"``:
    int8 storage, bf16 compute).  ``MXTPU_PREDICT_INT8=1`` sets it for
    kwarg-less C-ABI clients, like ``MXTPU_PREDICT_DTYPE``.
    """

    def __init__(self, symbol_json_str=None, param_bytes=None,
                 input_shapes=None, dev_type="cpu", dev_id=0,
                 symbol=None, arg_params=None, aux_params=None,
                 output_index=None, dtype=None, quantize=None):
        from . import context as ctx_mod
        from .executor import simple_bind

        if symbol is None:
            if symbol_json_str is None:
                raise MXNetError("need symbol or symbol_json_str")
            symbol = sym_mod.load_json(symbol_json_str)
        if arg_params is None:
            arg_params, aux_params = {}, {}
            if param_bytes is not None:
                loaded = _load_param_bytes(param_bytes)
                for k, v in loaded.items():
                    tp, name = k.split(":", 1)
                    if tp == "arg":
                        arg_params[name] = v
                    elif tp == "aux":
                        aux_params[name] = v
        aux_params = aux_params or {}

        # parity: MXPredCreatePartialOut — cut the graph at selected
        # internal outputs (by index, name, or list thereof)
        if output_index is not None:
            internals = symbol.get_internals()
            indices = output_index if isinstance(output_index, (list, tuple)) \
                else [output_index]
            picked = []
            names = internals.list_outputs()
            for sel in indices:
                if isinstance(sel, str):
                    if sel not in names:
                        raise MXNetError(
                            f"unknown output {sel!r}; internals: {names}")
                    picked.append(internals[names.index(sel)])
                elif isinstance(sel, int):
                    picked.append(internals[sel])
                else:
                    raise MXNetError(
                        f"output_index entries must be int or str, got {sel!r}")
            symbol = picked[0] if len(picked) == 1 else sym_mod.Group(picked)

        # inference-mode Conv+BN folding (passes/convbn.py): the predict
        # path never trains, so every frozen BatchNorm behind a conv is
        # folded into the conv's weights/bias BEFORE binding — and,
        # critically, before int8 quantization below computes per-channel
        # scales, so the scales see the folded dynamic range.  Runs on
        # the cut (output_index) symbol; MXTPU_GRAPH_PASSES gates it.
        from .passes import apply_convbn_fold

        symbol, arg_params, aux_params, self._n_bn_folded = \
            apply_convbn_fold(symbol, arg_params, aux_params)

        self.symbol = symbol
        self._input_names = [n for n in symbol.list_arguments()
                             if n not in arg_params]
        input_shapes = dict(input_shapes or {})
        missing = [n for n in self._input_names if n not in input_shapes]
        if missing:
            # label-style args (e.g. softmax_label) are not fed at
            # inference; infer their shapes from the given inputs and
            # bind zeros (the reference's predict path does the same by
            # treating outputs as plain activations without labels)
            try:
                arg_shapes, _, _ = symbol.infer_shape(**input_shapes)
                inferred = dict(zip(symbol.list_arguments(), arg_shapes))
                for n in missing:
                    input_shapes[n] = inferred[n]
            except Exception as e:
                raise MXNetError(
                    f"input_shapes missing for inputs {missing}") from e
            self._input_names = [n for n in self._input_names
                                 if n not in missing]
            label_args = set(missing)  # bound to zeros by design
        else:
            label_args = set()

        device = ctx_mod.Context(dev_type, dev_id) \
            if isinstance(dev_type, str) else dev_type
        self._exec = simple_bind(symbol, device, grad_req="null",
                                 **input_shapes)
        self._exec.copy_params_from(arg_params, aux_params,
                                    allow_extra_params=True)
        # every weight must have come from the checkpoint: simple_bind
        # leaves unset args at ZERO, so a silently-skipped load would
        # "work" and return uniform softmax outputs instead of failing
        uncovered = [n for n in self._exec.arg_dict
                     if n not in self._input_names and n not in arg_params
                     and n not in label_args]
        if uncovered:
            raise MXNetError(
                f"params file covers no value for {uncovered[:5]} "
                "(corrupt/truncated checkpoint, or name mismatch)")
        self._dirty = True

        if dtype is None:
            dtype = os.environ.get("MXTPU_PREDICT_DTYPE") or None
        if quantize is None and os.environ.get(
                "MXTPU_PREDICT_INT8", "0").lower() not in ("", "0", "false"):
            quantize = "int8"
        if quantize not in (None, "int8"):
            raise MXNetError(f"unknown quantize mode {quantize!r} "
                             "(supported: 'int8')")
        self._dtype = dtype  # normalized to a jnp dtype in _build_fast_forward
        self._quantize = quantize
        self._wire_dtype = None  # host-side upload dtype (set below)
        self._build_fast_forward()
        self._fast_outs = None
        self._inflight = {}   # ticket -> list of dispatched outputs
        self._inflight_lock = __import__("threading").Lock()
        self._ticket = 0
        self._step = 0

    def _build_fast_forward(self):
        """One jitted computation per Predictor: params/inputs → outputs.

        Unlike Executor.forward (which runs eager NDArray writes, an
        eager RNG fold, and output re-wrapping per call — each one a
        host↔device round trip that serializes on tunneled/remote
        backends), this path is a single dispatch: the RNG fold happens
        *inside* the program (the step counter is a traced scalar), the
        dtype casts fuse into their consumers, and outputs stay raw jax
        arrays until ``get_output`` copies them out (parity note: the
        reference forces the synchronous NaiveEngine for predict,
        include/mxnet/base.h:72-74 — here "synchronous" is simply one
        XLA program per forward)."""
        import jax
        import jax.numpy as jnp

        if getattr(self._exec, "_placed", False):
            self._infer_jit = None  # ctx-group graphs: outer must stay unjitted
            if self._dtype not in (None, "float32") or self._quantize:
                import warnings

                warnings.warn(
                    "Predictor dtype=%r / quantize=%r is not applied on "
                    "ctx-group (placed) graphs — the executor fallback "
                    "computes in the checkpoint's own precision"
                    % (self._dtype, self._quantize),
                    stacklevel=3)
            return
        graph_fn = self._exec._graph_fn
        cast = None if self._dtype is None else jnp.dtype(self._dtype)
        # weights are immutable after construction (set_input only accepts
        # declared inputs; reshape() builds a whole new Predictor), so
        # snapshot them once — forward() then only uploads the inputs
        self._param_snapshot = {
            k: v._read() for k, v in self._exec.arg_dict.items()
            if k not in self._input_names}
        self._aux_snapshot = {
            k: v._read() for k, v in self._exec.aux_dict.items()}
        # int8 weight quantization (serving/quantize.py): move the
        # filtered weights out of the fp snapshot into an int8+scale
        # tree; _infer dequantizes them INSIDE the program, directly in
        # the compute dtype, so storage is int8 and the multiply fuses
        # into each weight's consumer
        self._qparams = {}
        if self._quantize == "int8":
            from .serving.quantize import (default_weight_filter,
                                           quantize_per_channel)

            for k in list(self._param_snapshot):
                v = self._param_snapshot[k]
                if not default_weight_filter(k, v):
                    continue
                q, scale = quantize_per_channel(np.asarray(v), axis=0)
                self._qparams[k] = (jax.device_put(q),
                                    jax.device_put(scale))
                del self._param_snapshot[k]
        # upload inputs over the wire ALREADY in the compute dtype: the
        # in-graph cast would throw the upper half of every fp32 mantissa
        # away on arrival anyway, so casting on the host first halves the
        # host->device bytes — on transport-bound deployments (remote/
        # tunneled devices) input upload IS the predictor's bottleneck
        if cast is not None and cast != jnp.float32:
            self._wire_dtype = cast

        def _infer(params, qparams, aux, inputs, step, base_key):
            key = jax.random.fold_in(base_key, step)
            merged = dict(params)
            dq = cast if cast is not None else jnp.float32
            for k, (q, scale) in qparams.items():
                merged[k] = q.astype(dq) * scale.astype(dq)
            merged.update(inputs)
            if cast is not None and cast != jnp.float32:
                merged = {k: v.astype(cast) if v.dtype == jnp.float32 else v
                          for k, v in merged.items()}
                aux = {k: v.astype(cast) if v.dtype == jnp.float32 else v
                       for k, v in aux.items()}
            outs, _ = graph_fn(merged, aux, key, False)
            if cast is not None and cast != jnp.float32:
                outs = [o.astype(jnp.float32) if o.dtype == cast else o
                        for o in outs]
            return outs

        self._infer_jit = jax.jit(_infer)

    # ------------------------------------------------------------------ API
    def _coerce_input(self, name, value):
        """Validate name/shape and coerce to the bound dtype (shared by
        set_input and forward kwargs)."""
        if name not in self._input_names:
            raise MXNetError(f"unknown input {name}; inputs: {self._input_names}")
        arr = self._exec.arg_dict[name]
        value = np.asarray(value, dtype=arr.dtype)
        if value.shape != arr.shape:
            raise MXNetError(
                f"shape mismatch for {name}: got {value.shape}, bound {arr.shape}")
        return arr, value

    def _upload_input(self, name, value):
        """Single host→device transfer straight onto the bound array's
        device — no eager broadcast op, no default-device detour.

        The host value is copied first: jax's cpu backend may alias a
        numpy buffer zero-copy into the device array, so without the
        copy a caller that mutates (or frees — the C ABI case) its
        buffer after set_input would corrupt the bound input.  The copy
        restores the old ``arr[:] = value`` semantics at memcpy cost,
        negligible next to the transfer it precedes."""
        import jax

        arr, value = self._coerce_input(name, value)
        if self._wire_dtype is not None and value.dtype == np.float32:
            value = value.astype(self._wire_dtype)  # astype copies
        else:
            value = np.array(value, copy=True)
        arr._set(jax.device_put(value, arr._read().sharding))

    def set_input(self, name, value):
        """Parity: MXPredSetInput."""
        self._upload_input(name, value)
        self._dirty = True

    def forward(self, **inputs):
        """Parity: MXPredForward (kwargs are a convenience for set_input)."""
        if self._infer_jit is None:  # ctx-group fallback: executor path
            for name, value in inputs.items():
                self.set_input(name, value)
            self._exec.forward(is_train=False)
            self._fast_outs = None
            self._dirty = False
            return
        self._fast_outs = self._dispatch(inputs)

    def _dispatch(self, inputs):
        """Upload inputs and dispatch one forward (shared by forward and
        forward_async); returns the raw output arrays without joining."""
        from . import random as _random

        arg_dict = self._exec.arg_dict
        for name, value in inputs.items():
            self._upload_input(name, value)
        feeds = {n: arg_dict[n]._read() for n in self._input_names}
        # the key is a traced argument (not a closure constant) so a
        # later mx.random.seed() is honored, matching Executor.forward
        outs = self._infer_jit(
            self._param_snapshot, self._qparams, self._aux_snapshot,
            feeds, np.uint32(self._step), _random.current_key())
        self._step += 1
        self._dirty = False
        return outs

    def forward_async(self, **inputs):
        """Dispatch a forward WITHOUT joining it; returns a ticket for
        ``get_async``.  Several tickets may be in flight at once — each
        call's input upload, compute, and device→host output fetch queue
        independently, so consecutive calls pipeline all three stages
        against each other.  On transport-bound deployments (remote or
        tunneled devices) this hides compute and output-fetch time under
        the next call's input upload; a strict
        ``forward()``/``get_output()`` loop instead pays the full
        upload+compute+fetch round trip per call.

        The C ABI exposes this pair as MXPredForwardAsync /
        MXPredGetOutputAsync (src/c_predict.cc)."""
        if self._infer_jit is None:
            raise MXNetError("forward_async is not supported on ctx-group "
                             "(placed) graphs — use forward()")
        outs = self._dispatch(inputs)
        # get_output() after forward_async keeps last-forward-wins
        # semantics (this IS the most recent forward)
        self._fast_outs = outs
        for o in outs:
            start = getattr(o, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()  # fetch streams while later calls compute
                except Exception:  # noqa: BLE001 — fetch runs in get_async
                    break
        with self._inflight_lock:
            self._ticket += 1
            ticket = self._ticket
            self._inflight[ticket] = list(outs)
            # abandoned tickets (multi-output partial fetches, clients
            # that error out) must not pin device buffers forever: keep
            # at most 64 in flight, evicting oldest-first (dict preserves
            # insertion order) — a pipelined client holds a handful
            while len(self._inflight) > 64:
                self._inflight.pop(next(iter(self._inflight)))
        return ticket

    def get_async(self, ticket, index=0):
        """Join output ``index`` of an in-flight ``forward_async`` ticket
        as a host array.  Each output is fetchable once; the ticket
        retires after its last unfetched output is taken (or via
        ``discard_async``)."""
        with self._inflight_lock:
            outs = self._inflight.get(ticket)
            if outs is None:
                raise MXNetError(
                    f"unknown or already-retired ticket {ticket}")
            if not 0 <= index < len(outs) or outs[index] is None:
                raise MXNetError(
                    f"ticket {ticket}: output {index} is out of range or "
                    f"already fetched ({len(outs)} outputs)")
            out, outs[index] = outs[index], None
            if all(o is None for o in outs):
                del self._inflight[ticket]
        return np.asarray(out, dtype=np.float32) \
            if out.dtype != np.float32 else np.asarray(out)

    def discard_async(self, ticket):
        """Drop an in-flight ticket without fetching (frees its device
        output buffers); unknown tickets are a no-op."""
        with self._inflight_lock:
            self._inflight.pop(ticket, None)

    def partial_forward(self, step):
        """Parity: MXPredPartialForward — the reference runs the op
        sequence up to `step` for debugging.  XLA executes the graph as
        one fused computation, so partial execution is served from the
        internals graph: output `step` of get_internals()."""
        internals = self.symbol.get_internals()
        names = internals.list_outputs()
        step = min(step, len(names) - 1)
        sub = internals[step]
        shapes = {n: self._exec.arg_dict[n].shape for n in self._input_names}
        ex = sub.simple_bind(self._exec._ctx, grad_req="null", **shapes)
        ex.copy_params_from(
            {k: v for k, v in self._exec.arg_dict.items()
             if k not in self._input_names},
            dict(self._exec.aux_dict), allow_extra_params=True)
        for n in self._input_names:
            if n in ex.arg_dict:
                ex.arg_dict[n][:] = self._exec.arg_dict[n].asnumpy()
        ex.forward(is_train=False)
        return [o.asnumpy() for o in ex.outputs]

    def get_output_shape(self, index=0):
        """Parity: MXPredGetOutputShape — usable BEFORE the first forward
        (the reference computes output shapes at MXPredCreate so C clients
        can size their buffers, c_predict_api.cc)."""
        if self._fast_outs is not None:
            return tuple(self._fast_outs[index].shape)
        if self._exec._outputs_cache is None and self._exec._pending is None:
            shapes = {n: self._exec.arg_dict[n].shape
                      for n in self._input_names}
            _, out_shapes, _ = self.symbol.infer_shape(**shapes)
            return tuple(out_shapes[index])
        return tuple(self._exec.outputs[index].shape)

    def get_output(self, index=0):
        """Parity: MXPredGetOutput — blocking copy-out."""
        if self._dirty:
            self.forward()
        if self._fast_outs is not None:
            return np.asarray(self._fast_outs[index])
        return self._exec.outputs[index].asnumpy()

    @property
    def num_outputs(self):
        if self._fast_outs is not None:
            return len(self._fast_outs)
        return len(self.symbol.list_outputs())

    def _input_shape(self, name):
        """Bound shape of an input (used by the C ABI to reshape flat
        buffers, src/c_predict.cc)."""
        return tuple(self._exec.arg_dict[name].shape)

    def reshape(self, input_shapes):
        """Parity: MXPredReshape — rebind with new input shapes (the jit
        cache makes repeat shapes free)."""
        arg_params = {k: v for k, v in self._exec.arg_dict.items()
                      if k not in self._input_names}
        aux_params = dict(self._exec.aux_dict)
        new = Predictor(symbol=self.symbol, arg_params=arg_params,
                        aux_params=aux_params, input_shapes=input_shapes,
                        dev_type=self._exec._ctx,  # keep the original device
                        dtype=self._dtype, quantize=self._quantize)
        self.__dict__.update(new.__dict__)


def _load_param_bytes(param_bytes):
    import tempfile, os

    with tempfile.NamedTemporaryFile(suffix=".params", delete=False) as f:
        f.write(param_bytes)
        path = f.name
    try:
        return nd.load(path)
    finally:
        os.unlink(path)


def create(prefix, epoch, input_shapes, dev_type="cpu", dev_id=0,
           dtype=None, quantize=None):
    """Load a save_checkpoint()-style checkpoint into a Predictor
    (parity: the common MXPredCreate usage in c_predict_api examples)."""
    from .model import load_checkpoint

    symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
    return Predictor(symbol=symbol, arg_params=arg_params,
                     aux_params=aux_params, input_shapes=input_shapes,
                     dev_type=dev_type, dev_id=dev_id, dtype=dtype,
                     quantize=quantize)
