"""Symbolic graph layer.

Parity: python/mxnet/symbol.py + the NNVM symbol/graph IR the reference
imports (include/mxnet/base.h:14-17, src/nnvm/ bridges; SURVEY.md §1 layer
3).  The Symbol here is a lightweight DAG whose nodes reference ops in
mxnet_tpu.ops.registry.  There is no separate pass pipeline: the NNVM
passes map onto JAX machinery at bind time (SURVEY.md §7):

- Gradient        -> jax.vjp in the executor
- InferShape/Type -> graph walk with jax.eval_shape + per-op param hooks
- PlanMemory      -> XLA buffer assignment (+ donation in fused paths)
- PlaceDevice     -> ctx_group attrs consumed as sharding hints by the
                     executor/mesh layer (parallel/)

JSON round-trip keeps the reference's nodes/arg_nodes/heads structure
(nnvm::Graph save format) so checkpoints are portable in spirit.
"""
from __future__ import annotations

import hashlib
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import ops
from .base import MXNetError, current_attr_scope, current_name_manager

_py_slice = slice


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "extra_attrs", "is_aux")

    def __init__(self, op, name, attrs=None, inputs=None, extra_attrs=None, is_aux=False):
        self.op = op  # None for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs: List[Tuple[_Node, int]] = list(inputs or [])
        self.extra_attrs = dict(extra_attrs or {})
        self.is_aux = is_aux

    @property
    def is_variable(self):
        return self.op is None

    def num_outputs(self):
        if self.op is None:
            return 1
        od = ops.get(self.op)
        if od.num_outputs_fn is not None:
            return od.num_outputs_fn(self.attrs)
        if od.num_outputs == -1:  # attr-dependent (SliceChannel)
            return int(self.attrs.get("num_outputs", 1))
        return od.num_outputs


def _topo_order(out_nodes: Sequence[_Node]) -> List[_Node]:
    """Stable DFS topological order — matches the reference's IndexedGraph
    ordering so list_arguments() agrees with MXNet's."""
    seen = {}
    order: List[_Node] = []

    def visit(node):
        if id(node) in seen:
            return
        seen[id(node)] = True
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for n in out_nodes:
        visit(n)
    return order


class Symbol:
    """A list of output entries of a graph node (parity: nnvm::Symbol)."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs: Sequence[Tuple[_Node, int]]):
        self._outputs = list(outputs)

    # ------------------------------------------------------------- structure
    @property
    def nodes(self) -> List[_Node]:
        return _topo_order([n for n, _ in self._outputs])

    def list_arguments(self) -> List[str]:
        return [n.name for n in self.nodes if n.is_variable and not n.is_aux]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self.nodes if n.is_variable and n.is_aux]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
                continue
            od = ops.get(node.op)
            if od.num_outputs_fn is not None:
                names.append(f"{node.name}_{od.output_names[idx]}"
                             if idx < len(od.output_names) else f"{node.name}_output{idx}")
            elif od.num_outputs == -1:  # attr-dependent (SliceChannel)
                names.append(f"{node.name}_output{idx}")
            elif od.num_outputs == 1:
                names.append(f"{node.name}_output")
            else:
                names.append(f"{node.name}_{od.output_names[idx]}")
        return names

    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError(f"no output named {index}; outputs: {names}")
            index = names.index(index)
        if isinstance(index, int):
            return Symbol([self._outputs[index]])
        raise TypeError(index)

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield self[i]

    def structural_signature(self) -> str:
        """Structure hash for the executor's compiled-program cache.

        Two symbols with equal signatures evaluate identically through
        ``_build_graph_fn``: same op topology, op types, op attrs,
        variable names / aux flags / declared shapes+dtypes (``__shape__``
        and ``__dtype__`` live in extra_attrs), and the same output
        entries.  Node identity is deliberately NOT part of the key — a
        graph rebuilt from scratch (fresh ``simple_bind`` in tests or
        serving, a re-generated bucket symbol) hashes equal and reuses
        the already-jitted executables.  Names are in the key ONLY for
        variable nodes: they are the bind interface (arg/aux dicts key
        on them), while internal op-node names are presentation-only —
        ``_build_graph_fn`` never reads them.  Dropping them means
        alpha-renamed but identical graphs (fresh gensym suffixes from
        the NameManager counter across processes or re-generated bucket
        symbols) hit the program cache instead of recompiling.  Runtime
        input shapes/dtypes stay out of the key: ``jax.jit`` already
        caches per-aval under one compiled callable, which is exactly
        the reuse this enables.
        """
        nodes = self.nodes
        index = {id(n): i for i, n in enumerate(nodes)}
        parts = []
        for n in nodes:
            parts.append((
                n.op or "null",
                n.name if n.is_variable else "",
                n.is_aux,
                tuple(sorted((k, repr(v)) for k, v in n.attrs.items())),
                tuple(sorted((k, repr(v)) for k, v in n.extra_attrs.items())),
                tuple((index[id(src)], oidx) for src, oidx in n.inputs),
            ))
        heads = tuple((index[id(n)], i) for n, i in self._outputs)
        return hashlib.sha256(repr((parts, heads)).encode()).hexdigest()

    def get_internals(self) -> "Symbol":
        """Parity: Symbol.get_internals — every node's outputs, topo order."""
        outs = []
        for node in self.nodes:
            if node.is_variable:
                outs.append((node, 0))
            else:
                for i in range(node.num_outputs()):
                    outs.append((node, i))
        return Symbol(outs)

    def get_children(self) -> Optional["Symbol"]:
        node, _ = self._outputs[0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # ------------------------------------------------------------------ attrs
    def attr(self, key):
        node, _ = self._outputs[0]
        return node.extra_attrs.get(key)

    def list_attr(self):
        node, _ = self._outputs[0]
        return dict(node.extra_attrs)

    def attr_dict(self):
        out = {}
        for node in self.nodes:
            if node.extra_attrs:
                out[node.name] = dict(node.extra_attrs)
        return out

    def _set_attr(self, **kwargs):
        node, _ = self._outputs[0]
        node.extra_attrs.update({k: str(v) for k, v in kwargs.items()})

    # -------------------------------------------------------------- operators
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(op, [a, b], {})
        if np.isscalar(other):
            return _create(scalar_op, [self], {"scalar": float(other)})
        raise TypeError(type(other))

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        if np.isscalar(o):
            return _create("_rminus_scalar", [self], {"scalar": float(o)})
        return self._binop(o, "elemwise_sub", None, reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        if np.isscalar(o):
            return _create("_rdiv_scalar", [self], {"scalar": float(o)})
        return self._binop(o, "elemwise_div", None, reverse=True)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, o):
        return self._binop(o, "_power", "_power_scalar")

    def __neg__(self):
        return _create("negative", [self], {})

    def __repr__(self):
        name = self.name or "grouped"
        return f"<Symbol {name}>"

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # --------------------------------------------------------- shape inference
    def infer_shape(self, *args, **kwargs):
        """Parity: Symbol.infer_shape -> (arg_shapes, out_shapes, aux_shapes).

        Reference pipeline: nnvm InferShape pass (graph_executor.cc:404).
        Here: forward walk with per-op param hooks + jax.eval_shape.
        """
        try:
            return self._infer_and_collect(args, kwargs, partial=False)
        except _InferIncomplete:
            return None, None, None

    def infer_shape_partial(self, *args, **kwargs):
        """Parity: Symbol.infer_shape_partial — like infer_shape but
        returns whatever is inferable (None for the rest) instead of
        failing when some inputs are unknown."""
        return self._infer_and_collect(args, kwargs, partial=True)

    def _infer_and_collect(self, args, kwargs, partial):
        known = dict(kwargs)
        if args:
            for name, shape in zip(self.list_arguments(), args):
                if shape is not None:
                    known[name] = shape
        shapes, _ = self._infer(known, {}, partial=partial)

        def out_shape(node, idx):
            if node.is_variable:  # variables are keyed by name, not node id
                return shapes.get((node.name, "var"))
            return shapes.get((id(node), idx))

        arg_shapes = [shapes.get((a, "var")) for a in self.list_arguments()]
        aux_shapes = [shapes.get((a, "var")) for a in self.list_auxiliary_states()]
        out_shapes = [out_shape(n, i) for n, i in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        known = dict(kwargs)
        if args:
            for name, ty in zip(self.list_arguments(), args):
                if ty is not None:
                    known[name] = ty
        shape_known = {}
        # infer_type alone (no shapes) falls back to float32 everywhere
        arg_types = [np.dtype(known.get(a, np.float32)).type for a in self.list_arguments()]
        aux_types = [np.float32 for _ in self.list_auxiliary_states()]
        out_types = [np.float32 for _ in self._outputs]
        return arg_types, out_types, aux_types

    def _infer(self, known_shapes: Dict[str, tuple], known_types: Dict[str, type],
               partial: bool = False):
        """Walk the graph computing avals; returns ({key: shape}, {key: dtype})
        with keys (arg_name,'var') for variables and (id(node), out_idx).
        With partial=True, nodes that cannot be inferred are skipped
        (their consumers skip too) instead of aborting the walk."""
        shapes: Dict = {}
        dtypes: Dict = {}
        avals: Dict = {}  # id(node) -> tuple of ShapeDtypeStruct

        def var_aval(node):
            name = node.name
            if name in known_shapes:
                shape = tuple(known_shapes[name])
            elif "__shape__" in node.extra_attrs:
                shape = tuple(json.loads(node.extra_attrs["__shape__"]))
            else:
                return None
            dt = np.dtype(known_types.get(name, np.float32))
            return jax.ShapeDtypeStruct(shape, dt)

        def eval_node(node):
            od = ops.get(node.op)
            in_avals = []
            unknown_vars = []
            for inp, oidx in node.inputs:
                got = avals.get(id(inp))
                if got is None:
                    if inp.is_variable:
                        unknown_vars.append(inp)
                        in_avals.append(None)
                    else:
                        raise _InferIncomplete(node.name)
                else:
                    in_avals.append(got[oidx])
            if unknown_vars:
                if od.infer_params is None:
                    raise _InferIncomplete(node.name)
                known_in = [a.shape if a is not None else None for a in in_avals]
                try:
                    param_shapes = od.infer_params(node.attrs, *known_in)
                except (TypeError, IndexError, KeyError):
                    # hook needs shapes we don't have yet (e.g. data unknown)
                    raise _InferIncomplete(node.name) from None
                arg_names = od.resolve_arg_names(node.attrs) + list(od.aux_names)
                for j, (inp, _) in enumerate(node.inputs):
                    if in_avals[j] is None:
                        pname = arg_names[j] if j < len(arg_names) else None
                        if pname not in param_shapes:
                            raise _InferIncomplete(f"{node.name}:{pname}")
                        av = jax.ShapeDtypeStruct(tuple(param_shapes[pname]), np.float32)
                        avals[id(inp)] = (av,)
                        shapes[(inp.name, "var")] = av.shape
                        dtypes[(inp.name, "var")] = av.dtype
                        in_avals[j] = av
            out_avals = _abstract_eval(od, node.attrs, in_avals)
            avals[id(node)] = out_avals
            for i, av in enumerate(out_avals):
                shapes[(id(node), i)] = av.shape
                dtypes[(id(node), i)] = av.dtype

        for node in self.nodes:
            if node.is_variable:
                av = var_aval(node)
                if av is not None:
                    avals[id(node)] = (av,)
                    shapes[(node.name, "var")] = av.shape
                    dtypes[(node.name, "var")] = av.dtype
                continue
            try:
                eval_node(node)
            except _InferIncomplete:
                if not partial:
                    raise
        return shapes, dtypes

    # -------------------------------------------------------------- save/load
    def tojson(self) -> str:
        """Parity: nnvm JSON (save format of MXSymbolSaveToJSON)."""
        nodes = self.nodes
        index = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append(
                {
                    "op": n.op or "null",
                    "name": n.name,
                    "attrs": {k: json.dumps(v) if not isinstance(v, str) else v
                              for k, v in n.attrs.items()},
                    "extra_attrs": n.extra_attrs,
                    "is_aux": n.is_aux,
                    "inputs": [[index[id(src)], oidx, 0] for src, oidx in n.inputs],
                }
            )
        heads = [[index[id(n)], i, 0] for n, i in self._outputs]
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable]
        return json.dumps(
            {"nodes": jnodes, "arg_nodes": arg_nodes, "heads": heads,
             "attrs": {"mxnet_tpu_version": 1}},
            indent=2,
        )

    def save(self, fname: str):
        from .filesystem import is_remote, open_uri

        if is_remote(fname):
            with open_uri(fname, "wb") as f:
                f.write(self.tojson().encode())
            return
        with open(fname, "w") as f:
            f.write(self.tojson())

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, shardings=None,
                    **kwargs):
        from .executor import simple_bind as _simple_bind

        return _simple_bind(self, ctx, grad_req=grad_req, type_dict=type_dict,
                            group2ctx=group2ctx, shared_exec=shared_exec,
                            shardings=shardings, **kwargs)

    # convenience evaluation (imperative-style) used by tests
    def eval(self, ctx=None, **kwargs):
        ex = self.simple_bind(ctx, grad_req="null",
                              **{k: v.shape for k, v in kwargs.items()})
        for k, v in kwargs.items():
            ex.arg_dict[k][:] = v
        return ex.forward(is_train=False)


class _InferIncomplete(Exception):
    pass


def _abstract_eval(od, attrs, in_avals):
    """Output avals of one op via jax.eval_shape."""

    def fn(*ins):
        ctx = ops.OpCtx(is_train=True, key=jax.random.PRNGKey(0))
        res = od.fn(ctx, *ins, **attrs)
        if od.aux_names:
            res = res[0]
        return res

    out = jax.eval_shape(fn, *in_avals)
    if isinstance(out, (tuple, list)):
        return tuple(out)
    return (out,)


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------
def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, grad_stype=None,
             **kwargs) -> Symbol:
    """Parity: mx.sym.Variable (symbol.py in reference).

    ``grad_stype="row_sparse"`` marks an Embedding weight for row-sparse
    gradient emission (docs/sparse.md): the executor's backward returns
    the coalesced ``(indices, values)`` pair of touched rows instead of
    a table-sized dense scatter.  ``stype`` is accepted for reference
    API parity and recorded as an annotation (storage here is dense
    device arrays; the sparse *gradient* path is what the TPU port
    optimizes)."""
    scope = current_attr_scope()
    extra = scope.get(attr) if scope else dict(attr or {})
    if shape is not None:
        extra["__shape__"] = json.dumps(list(shape))
    if lr_mult is not None:
        extra["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        extra["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        extra["__dtype__"] = np.dtype(dtype).name
    if init is not None:
        extra["__init__"] = init if isinstance(init, str) else init.dumps()
    for key, val in (("__storage_type__", stype),
                     ("__grad_stype__", grad_stype)):
        if val is not None:
            if val not in ("default", "row_sparse"):
                raise MXNetError(
                    f"Variable {name!r}: unknown storage type {val!r} "
                    "(expected 'default' or 'row_sparse')")
            extra[key] = val
    node = _Node(None, name, extra_attrs=extra)
    return Symbol([(node, 0)])


def Group(symbols) -> Symbol:
    """Parity: mx.sym.Group."""
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _create(opname, sym_inputs, attrs, name=None, extra_attr=None) -> Symbol:
    """Create an op node (parity: the C API symbol creation path
    MXSymbolCreateAtomicSymbol + Compose)."""
    od = ops.get(opname)
    name = current_name_manager().get(name, od.name)
    scope = current_attr_scope()
    extra = scope.get(extra_attr) if scope else dict(extra_attr or {})

    inputs: List[Tuple[_Node, int]] = []
    for s in sym_inputs:
        if not isinstance(s, Symbol):
            raise TypeError(f"{opname}: expected Symbol input, got {type(s)}")
        if len(s._outputs) != 1:
            raise MXNetError(f"{opname}: cannot use a grouped symbol as input")
        inputs.append(s._outputs[0])

    node = _Node(od.name, name, attrs=attrs, inputs=inputs, extra_attrs=extra)
    n_out = node.num_outputs()
    return Symbol([(node, i) for i in range(n_out)]) if n_out > 1 else Symbol([(node, 0)])


def _make_symbol_fn(opname: str):
    od = ops.get(opname)

    def creator(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        od_local = ops.get(opname)
        sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        attrs = {k: v for k, v in kwargs.items() if not isinstance(v, Symbol)}
        name = current_name_manager().get(name, od_local.name)

        if od_local.varargs:
            inputs = [a for a in args if isinstance(a, Symbol)]
            attrs.setdefault("num_args", len(inputs))
            sym = _create_named(od_local, inputs, attrs, name, attr)
            return sym

        arg_names = od_local.resolve_arg_names(attrs)
        inputs = []
        pos = list(args)
        for an in arg_names:
            if an in sym_kwargs:
                inputs.append(sym_kwargs.pop(an))
            elif pos:
                inputs.append(pos.pop(0))
            else:
                # auto-create variable (param or missing data/label input) —
                # parity: symbol composition creates e.g. conv0_weight,
                # softmax_label (reference symbol.py Compose behavior)
                inputs.append(Variable(f"{name}_{an}"))
        if sym_kwargs:
            raise MXNetError(f"{opname}: unexpected symbol kwargs {list(sym_kwargs)}")
        for aux in od_local.aux_names:
            v = Variable(f"{name}_{aux}")
            v._outputs[0][0].is_aux = True
            inputs.append(v)
        return _create_named(od_local, inputs, attrs, name, attr)

    creator.__name__ = opname
    creator.__doc__ = od.doc
    return creator


def _create_named(od, sym_inputs, attrs, name, extra_attr):
    scope = current_attr_scope()
    extra = scope.get(extra_attr) if scope else dict(extra_attr or {})
    inputs = []
    for s in sym_inputs:
        if len(s._outputs) != 1:
            raise MXNetError(f"{od.name}: cannot use grouped symbol as input")
        inputs.append(s._outputs[0])
    node = _Node(od.name, name, attrs=attrs, inputs=inputs, extra_attrs=extra)
    n_out = node.num_outputs()
    return Symbol([(node, i) for i in range(n_out)])


def load(fname: str) -> Symbol:
    from .filesystem import is_remote, open_uri

    if is_remote(fname):
        with open_uri(fname, "rb") as f:
            return load_json(f.read().decode())
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str: str) -> Symbol:
    """Parity: MXSymbolCreateFromJSON.

    Reference/nnvm-format JSON (node ``param`` dicts, backward_source_id,
    node_row_ptr — anything not written by this package's tojson) routes
    through interop.load_symbol_json, which also applies the legacy
    upgrades (aux-input injection etc.)."""
    data = json.loads(json_str)
    if "nodes" in data and data["nodes"] and (
            "node_row_ptr" in data
            or any("param" in n or "backward_source_id" in n
                   for n in data["nodes"])):
        from .interop import load_symbol_json

        return load_symbol_json(json_str)
    nodes: List[_Node] = []
    for jn in data["nodes"]:
        if jn["op"] == "null":
            node = _Node(None, jn["name"], extra_attrs=jn.get("extra_attrs", {}),
                         is_aux=jn.get("is_aux", False))
        else:
            attrs = {}
            for k, v in jn.get("attrs", {}).items():
                try:
                    attrs[k] = json.loads(v)
                except (json.JSONDecodeError, TypeError):
                    attrs[k] = v
            node = _Node(jn["op"], jn["name"], attrs=attrs,
                         extra_attrs=jn.get("extra_attrs", {}))
            node.inputs = [(nodes[i], oidx) for i, oidx, _ in jn["inputs"]]
        nodes.append(node)
    heads = [(nodes[i], oidx) for i, oidx, _ in data["heads"]]
    return Symbol(heads)


def _init_symbol_functions():
    mod = sys.modules[__name__]
    all_ops = ops.list_ops()
    registered = set(all_ops)
    for opname in all_ops:
        if not hasattr(mod, opname):
            setattr(mod, opname, _make_symbol_fn(opname))
    for opname in all_ops:
        low = opname.lower()
        if low != opname and low not in registered and not hasattr(mod, low):
            setattr(mod, low, _make_symbol_fn(opname))


def zeros(shape, dtype=np.float32, **kwargs):
    return _create("_zeros", [], {"shape": tuple(shape), "dtype": np.dtype(dtype).name}, **kwargs)


def ones(shape, dtype=np.float32, **kwargs):
    return _create("_ones", [], {"shape": tuple(shape), "dtype": np.dtype(dtype).name}, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, dtype=np.float32, **kwargs):
    return _create(
        "_arange",
        [],
        {"start": start, "stop": stop, "step": step, "repeat": repeat,
         "dtype": np.dtype(dtype).name},
        **kwargs,
    )


_init_symbol_functions()
