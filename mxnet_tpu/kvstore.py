"""KVStore — parameter aggregation / synchronization.

Parity: include/mxnet/kvstore.h + src/kvstore/ (reference).  Semantics map:

- ``local`` / ``local_allreduce_cpu``: single-process aggregation; grads
  from all devices summed into a merge buffer, updater applied, result
  broadcast (reference KVStoreLocal, src/kvstore/kvstore_local.h:22-130 +
  CommCPU, comm.h:61-180).
- ``device`` / ``local_allreduce_device``: same API; reduction happens on
  accelerator.  On TPU the "P2P copies + ElementwiseSum with load-balanced
  merge buffers" machinery (CommDevice, comm.h:200-360) collapses into an
  XLA reduction — when used inside a pjit'd step it is an ICI all-reduce
  inserted by GSPMD (SURVEY.md §7 KVStore row).
- ``dist_sync`` / ``dist_device_sync`` / ``dist_async``: multi-process
  parameter-server roles (reference kvstore_dist*.h over ps-lite).  On TPU
  pods the synchronous flavors are DCN/ICI collectives via
  jax.distributed + the same mesh machinery (parallel/dist.py); the
  classes here keep rank/num_workers/barrier API parity for single-process
  use and raise if a true multi-process launch isn't active.

Push/pull keep the reference's per-key priority contract (each layer's
gradient communicated as soon as backward emits it — SURVEY.md §3.4): on
TPU, XLA's async dispatch provides the overlap, and the fused-step path
turns per-key psums into one bucketed all-reduce.
"""
from __future__ import annotations

import os
import pickle
from typing import Callable, Dict, List, Optional

from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray


def _key_list(key):
    return (key if isinstance(key, (list, tuple)) else [key]), not isinstance(key, (list, tuple))


class KVStore:
    """Parity: include/mxnet/kvstore.h:26-286 + python/mxnet/kvstore.py."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store: Dict = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None

    # ------------------------------------------------------------------ basic
    def init(self, key, value):
        """Parity: KVStore::Init — must be called once per key."""
        keys, _ = _key_list(key)
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError(f"duplicate init of key {k}")
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Parity: KVStore::Push.  value may be one NDArray or a list of
        per-device NDArrays — lists are reduced (summed) like Comm::Reduce
        (src/kvstore/comm.h:212-254)."""
        keys, single = _key_list(key)
        if single:
            values = [value]
        else:
            values = value
        for k, v in zip(keys, values):
            if isinstance(v, (list, tuple)):
                merged = v[0].copy()
                for other in v[1:]:
                    merged += other.as_in_context(merged.context)
            else:
                merged = v.copy()
            if self._updater is not None:
                self._updater(k if isinstance(k, int) else k, merged, self._store[k])
            else:
                # aggregation-only mode: stored value replaced by merged grad
                self._store[k]._set(merged._read())

    def pull(self, key, out=None, priority=0):
        """Parity: KVStore::Pull — copy current value into every out array
        (Comm::Broadcast, comm.h:256-274)."""
        keys, single = _key_list(key)
        outs = [out] if isinstance(out, NDArray) else out
        if single and isinstance(out, (list, tuple)):
            for o in out:
                self._store[keys[0]].copyto(o)
            return
        for k, o in zip(keys, outs):
            if isinstance(o, (list, tuple)):
                for oo in o:
                    self._store[k].copyto(oo)
            else:
                self._store[k].copyto(o)

    # -------------------------------------------------------------- optimizer
    def set_optimizer(self, optimizer):
        """Parity: kvstore.py set_optimizer — runs the optimizer inside the
        store (update_on_kvstore mode; server-side for dist)."""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    set_updater = _set_updater

    # ------------------------------------------------------------ distributed
    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    def barrier(self):
        pass

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def send_command_to_servers(self, head, body):
        pass

    def get_num_dead_node(self, node_id, timeout=60):
        """Parity: KVStore::get_num_dead_node (kvstore.h:242) — in-process
        stores have no remote nodes."""
        return 0


class KVStoreDist(KVStore):
    """Multi-worker kvstore over jax.distributed (parity:
    src/kvstore/kvstore_dist.h — the ps-lite worker client).

    On TPU pods, jax.distributed.initialize() wires the processes; sync
    aggregation rides DCN/ICI collectives executed inside the training
    step rather than an external parameter server.  Single-process runs
    degrade to local semantics with rank 0/size 1, matching how the
    reference behaves when launched without a tracker.
    """

    def __init__(self, kv_type):
        super().__init__(kv_type)
        self._rank = int(os.environ.get("MXNET_TPU_RANK",
                                        os.environ.get("DMLC_RANK", "0")))
        self._size = int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                                        os.environ.get("DMLC_NUM_WORKER", "1")))

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def barrier(self):
        # with a live jax.distributed backend this is a cross-host sync
        try:
            import jax

            if jax.process_count() > 1:
                from .parallel import dist as _dist

                _dist.barrier()
        except Exception:
            pass


def create(name="local") -> KVStore:
    """Parity: mx.kv.create (kvstore.py:385) + type parsing
    (src/kvstore/kvstore.cc:17-45)."""
    if not isinstance(name, str):
        raise TypeError("name must be str")
    if "dist" in name:
        return KVStoreDist(name)
    if name in ("local", "device", "local_allreduce_cpu",
                "local_allreduce_device", "local_update_cpu"):
        return KVStore(name)
    raise MXNetError(f"unknown kvstore type {name}")
