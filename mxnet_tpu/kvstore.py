"""KVStore — parameter aggregation / synchronization.

Parity: include/mxnet/kvstore.h + src/kvstore/ (reference).  Semantics map:

- ``local`` / ``local_allreduce_cpu``: single-process aggregation; grads
  from all devices summed into a merge buffer, updater applied, result
  broadcast (reference KVStoreLocal, src/kvstore/kvstore_local.h:22-130 +
  CommCPU, comm.h:61-180).
- ``device`` / ``local_allreduce_device``: same API; reduction happens on
  accelerator.  On TPU the "P2P copies + ElementwiseSum with load-balanced
  merge buffers" machinery (CommDevice, comm.h:200-360) collapses into an
  XLA reduction — when used inside a pjit'd step it is an ICI all-reduce
  inserted by GSPMD (SURVEY.md §7 KVStore row).
- ``dist_sync`` / ``dist_device_sync`` / ``dist_async``: multi-process
  parameter-server roles (reference kvstore_dist*.h over ps-lite).  On TPU
  pods the synchronous flavors are DCN/ICI collectives via
  jax.distributed + the same mesh machinery (parallel/dist.py); the
  classes here keep rank/num_workers/barrier API parity for single-process
  use and raise if a true multi-process launch isn't active.

Push/pull keep the reference's per-key priority contract (each layer's
gradient communicated as soon as backward emits it — SURVEY.md §3.4): on
TPU, XLA's async dispatch provides the overlap, and the fused-step path
turns per-key psums into one bucketed all-reduce.

Batched ``push(keys, grads)`` / ``pull(keys, outs)`` calls on a store
whose optimizer exposes a fused rule are routed to the bucketed
jit-fused update engine (kvstore_fused.py): size-capped flat buckets,
one compiled reduction + one jitted multi-tensor optimizer program per
bucket, device-resident state.  ``MXTPU_FUSED_UPDATE=0`` restores the
eager per-key loops, which also remain the path for ``dist_*`` stores,
custom updaters, and unsupported optimizers.
"""
from __future__ import annotations

import logging
import os
import pickle
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from . import ndarray as nd
from . import telemetry as _tm
from .base import MXNetError
from .ndarray import NDArray

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_PUSH = _tm.counter(
    "kvstore_push_total", "per-key push operations", labels=("store",))
_TM_PUSH_BYTES = _tm.counter(
    "kvstore_push_bytes_total",
    "logical payload bytes pushed (per key, post-merge)", labels=("store",))
_TM_PUSH_SEC = _tm.histogram(
    "kvstore_push_seconds",
    "per-key push latency (local: reduce+update dispatch; dist: the RPC)",
    labels=("store",))
_TM_PULL = _tm.counter(
    "kvstore_pull_total", "per-key pull operations", labels=("store",))
_TM_PULL_BYTES = _tm.counter(
    "kvstore_pull_bytes_total",
    "logical payload bytes pulled (per key, one copy per out array)",
    labels=("store",))
_TM_PULL_SEC = _tm.histogram(
    "kvstore_pull_seconds",
    "per-key pull latency (local: broadcast dispatch; dist: the RPC)",
    labels=("store",))
_TM_DIST_RETRY = _tm.counter(
    "kvstore_dist_retries_total",
    "KVStoreDist RPC attempts retried after a transport failure "
    "(broken pipe / reset / injected drop); each retry reconnects with "
    "exponential backoff + jitter and retransmits idempotently by "
    "request id", labels=("op",))
_TM_DEAD_WORKERS = _tm.gauge(
    "kvstore_dead_workers",
    "worker ranks whose heartbeats went stale (PS: the server-side "
    "staleness the client unions via get_num_dead_node; collective: "
    "hosts the coordinator declared dead); also surfaced in /healthz")


def dist_retries() -> int:
    """MXTPU_DIST_RETRIES — transport retries per RPC (default 5)."""
    try:
        return max(int(os.environ.get("MXTPU_DIST_RETRIES", "5")), 0)
    except ValueError:
        return 5


def dist_backoff_ms() -> float:
    """MXTPU_DIST_BACKOFF_MS — base retry backoff (default 50ms,
    doubled per attempt with jitter, capped at 5s)."""
    try:
        return max(float(os.environ.get("MXTPU_DIST_BACKOFF_MS", "50")),
                   1.0)
    except ValueError:
        return 50.0


def _nbytes(arr) -> int:
    return int(arr.size) * np.dtype(arr.dtype).itemsize


def _key_list(key):
    return (key if isinstance(key, (list, tuple)) else [key]), not isinstance(key, (list, tuple))


def _check_pairs(keys, values, op, what="values"):
    """A key list zipped against a mismatched value list would silently
    truncate to the shorter side — drop the check and a caller passing
    99 grads for 100 keys trains 99 params and never learns why."""
    if values is None or len(keys) != len(values):
        got = "None" if values is None else str(len(values))
        raise MXNetError(
            f"KVStore.{op}: got {len(keys)} keys but {got} {what}")


class KVStore:
    """Parity: include/mxnet/kvstore.h:26-286 + python/mxnet/kvstore.py."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store: Dict = {}
        # per-key storage type fixed at init ("default"/"row_sparse"):
        # a push whose value stype disagrees raises instead of silently
        # training the wrong math (docs/sparse.md)
        self._stypes: Dict = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None
        # 'device'-class stores reduce on-device with per-key merge
        # buffers load-balanced across the grads' devices (parity:
        # CommDevice::InitMergeBuffer, src/kvstore/comm.h:321-348)
        self._device_mode = kv_type in ("device", "local_allreduce_device")
        self._merge_ctx: Dict = {}
        self._merge_load: Dict = {}
        # bucketed jit-fused update engine (kvstore_fused.py), built by
        # set_optimizer when the optimizer has a fused rule
        self._fused = None
        # collective dist mode (KVStoreDist without a PS transport):
        # cross-host aggregation rides in-trace mesh collectives through
        # the fused/sharded bucket engine instead of per-key RPCs
        self._collective = False

    @property
    def collective(self) -> bool:
        """True for a dist store whose sync aggregation rides mesh
        collectives (no PS transport): callers batch push/pull like a
        local store — one bucketed dispatch per step, zero per-batch
        host syncs — instead of the per-key RPC priority loop."""
        return self._collective

    def _merge_context(self, k, vals):
        """Pick (once per key) the least-loaded device among the pushed
        copies for the merge buffer.  Spreading keys across devices gives
        aggregate reduction bandwidth, and since every jax dispatch is
        async, different keys reduce concurrently on their own devices —
        the engine-free analogue of the reference's priority-scheduled
        per-key overlap (SURVEY §3.4)."""
        ctx = self._merge_ctx.get(k)
        if ctx is None:
            cands = sorted({v.context for v in vals}, key=repr)
            ctx = min(cands, key=lambda c: self._merge_load.get(c, 0))
            self._merge_load[ctx] = (
                self._merge_load.get(ctx, 0)
                + vals[0].size * np.dtype(vals[0].dtype).itemsize)
            self._merge_ctx[k] = ctx
            if k in self._store:
                # in-store optimizer updates then run device-side too
                self._store[k] = self._store[k].as_in_context(ctx)
        return ctx

    # ------------------------------------------------------------------ basic
    def init(self, key, value):
        """Parity: KVStore::Init — must be called once per key.  A
        ``RowSparseNDArray`` value marks the key row-sparse: pushes must
        then be row-sparse (touched-rows-only updates); the stored table
        itself stays a dense device array (every row exists — sparsity
        here is a *gradient* property, SURVEY §KVStore)."""
        keys, _ = _key_list(key)
        values = value if isinstance(value, (list, tuple)) else [value]
        _check_pairs(keys, values, "init")
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError(f"duplicate init of key {k}")
            stype = getattr(v, "stype", "default")
            self._stypes[k] = stype
            self._store[k] = v.todense() if stype == "row_sparse" \
                else v.copy()

    def push(self, key, value, priority=0):
        """Parity: KVStore::Push.  value may be one NDArray or a list of
        per-device NDArrays — lists are reduced (summed) like Comm::Reduce
        (src/kvstore/comm.h:212-254)."""
        from . import faults as _faults

        _faults.maybe_fail("kv_push")
        keys, single = _key_list(key)
        if single:
            values = [value]
        else:
            values = value
            _check_pairs(keys, values, "push")
        self._check_push_stypes(keys, values)
        if (self._fused is not None and not single
                and self._fused.handle_push(keys, values)):
            return
        if self._fused is not None:
            # about to run the eager per-key loop: any sharded
            # optimizer state must land back in the per-key NDArrays
            # the Updater reads (no-op when nothing is sharded)
            self._fused.ensure_host_state()
        for k, v in zip(keys, values):
            vlist = v if isinstance(v, (list, tuple)) else [v]
            if getattr(vlist[0], "stype", "default") == "row_sparse":
                self._push_row_sparse(k, vlist)
                continue
            t0 = time.perf_counter() if _tm.enabled() else None
            if isinstance(v, (list, tuple)):
                if self._device_mode:
                    # reduce on the key's merge device: async copies in
                    # (CopyFromTo/P2P parity) + on-device sum; dispatch
                    # returns immediately, so reductions for this key
                    # overlap with the caller's remaining backward work
                    mctx = self._merge_context(k, v)
                    merged = v[0].copyto(mctx)
                    for other in v[1:]:
                        merged += other.as_in_context(mctx)
                else:
                    merged = v[0].copy()
                    for other in v[1:]:
                        merged += other.as_in_context(merged.context)
            else:
                merged = v.copy()
            if t0 is not None:
                _TM_PUSH.inc(store=self.type)
                _TM_PUSH_BYTES.inc(_nbytes(merged), store=self.type)
            if self._updater is not None:
                # the update must run where the stored weight lives: for
                # 'local' stores that is host memory (parity: CommCPU
                # reduces into pinned_ctx_, comm.h:74-130), for 'device'
                # stores the merge device (weight moved in _merge_context).
                # Without this, a TPU-resident grad meeting a host-resident
                # weight is a cross-platform op error.
                merged = merged.as_in_context(self._store[k].context)
                self._updater(k if isinstance(k, int) else k, merged, self._store[k])
            else:
                # aggregation-only mode: stored value replaced by merged grad
                self._store[k]._set(merged._read())
            if t0 is not None:
                _TM_PUSH_SEC.observe(time.perf_counter() - t0,
                                     store=self.type)

    def _check_push_stypes(self, keys, values):
        """Reject stype-mismatched pushes (ISSUE-9 satellite): a
        row-sparse gradient landing on a dense-initialized key (or a
        dense gradient on a row-sparse key) is never what the caller
        meant — the dense path would scatter garbage, the sparse path
        would decay rows it should not touch."""
        for k, v in zip(keys, values):
            v0 = v[0] if isinstance(v, (list, tuple)) and v else v
            vstype = getattr(v0, "stype", "default")
            if isinstance(v, (list, tuple)):
                for other in v[1:]:
                    if getattr(other, "stype", "default") != vstype:
                        raise MXNetError(
                            f"KVStore.push: key {k!r} received mixed "
                            "storage types across device copies")
            kstype = self._stypes.get(k)
            if kstype is not None and vstype != kstype:
                raise MXNetError(
                    f"KVStore.push: key {k!r} was initialized "
                    f"{kstype!r} but received a {vstype!r} value; "
                    "init the key with the matching storage type "
                    "(mx.nd.sparse / dense NDArray)")

    def _push_row_sparse(self, k, vlist):
        """Eager per-key row-sparse push: concat the per-device pairs
        (the segment-sum inside the row program does the cross-device
        reduce) and run the lazy touched-rows-only update through the
        Updater.  The fused engine's sparse buckets are the batched
        form of exactly this."""
        from . import sparse as _sparse

        t0 = time.perf_counter() if _tm.enabled() else None
        merged = _sparse.concat_rows(vlist)
        if self._updater is not None:
            self._updater(k if isinstance(k, int) else k, merged,
                          self._store[k])
        else:
            # aggregation-only mode: the merged (uncoalesced) gradient
            # replaces the stored value; pull hands it back row-sparse
            self._store[k] = merged.copy()
        if t0 is not None:
            _TM_PUSH.inc(store=self.type)
            _TM_PUSH_BYTES.inc(_nbytes(merged.data) + _nbytes(merged.indices),
                               store=self.type)
            _TM_PUSH_SEC.observe(time.perf_counter() - t0, store=self.type)
            _sparse._TM_SPARSE_ROWS.inc(int(merged.indices.shape[0]),
                                        store=self.type)
            _sparse._TM_SPARSE_DENSITY.observe(
                merged.indices.shape[0] / max(merged.shape[0], 1),
                store=self.type)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Parity: KVStore.row_sparse_pull — fetch ONLY the requested
        rows of a row-sparse key as a ``RowSparseNDArray`` (the pull
        half of the sparse contract: a worker holding a shard of the
        batch never materializes the full table).  ``row_ids`` is an
        NDArray / array-like of row indices (duplicates allowed, order
        preserved)."""
        from . import sparse as _sparse

        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        keys, single = _key_list(key)
        outs = [out] if out is None or isinstance(
            out, NDArray) else list(out)
        ids_list = [row_ids] if not isinstance(
            row_ids, (list, tuple)) else list(row_ids)
        if len(ids_list) != len(keys):
            raise MXNetError(
                f"row_sparse_pull: got {len(keys)} keys but "
                f"{len(ids_list)} row_ids")
        results = []
        for k, o, ids in zip(keys, outs, ids_list):
            if self._stypes.get(k) != "row_sparse":
                raise MXNetError(
                    f"row_sparse_pull: key {k!r} was initialized "
                    f"{self._stypes.get(k, 'default')!r}, not "
                    "'row_sparse'")
            t0 = time.perf_counter() if _tm.enabled() else None
            stored = self._store[k]
            if getattr(stored, "stype", "default") == "row_sparse":
                stored = stored.todense()  # aggregation-mode grads
            raw = stored._read()
            import jax.numpy as jnp

            idx = jnp.asarray(
                ids.asnumpy() if isinstance(ids, NDArray)
                else np.asarray(ids), dtype=jnp.int32).reshape(-1)
            rows = jnp.take(raw, idx, axis=0)
            if o is None:
                o = _sparse.RowSparseNDArray(NDArray(idx), NDArray(rows),
                                             tuple(raw.shape))
            else:
                if getattr(o, "stype", "default") != "row_sparse":
                    raise MXNetError(
                        "row_sparse_pull: out must be a "
                        "RowSparseNDArray")
                o._set_rows(idx, rows)
            results.append(o)
            if t0 is not None:
                self._record_pull(k, 1)
                _TM_PULL_SEC.observe(time.perf_counter() - t0,
                                     store=self.type)
        return results[0] if single else results

    def pull(self, key, out=None, priority=0):
        """Parity: KVStore::Pull — copy current value into every out array
        (Comm::Broadcast, comm.h:256-274).

        Storage-type rules (docs/sparse.md): a row-sparse out array on a
        dense key raises (use ``row_sparse_pull`` on a row-sparse key
        for row subsets); a DENSE out on a row-sparse key densifies —
        the stored table is a dense device array, so this is the
        whole-table broadcast the Module weight pull performs."""
        from . import faults as _faults

        _faults.maybe_fail("kv_pull")
        keys, single = _key_list(key)
        outs = [out] if isinstance(out, NDArray) else out
        if single and isinstance(out, (list, tuple)):
            # single-key fan-out fast path — timed like the main loop
            # (it used to record count/bytes but skip the latency
            # histogram, leaving kvstore_pull_seconds under-counted)
            t0 = time.perf_counter() if _tm.enabled() else None
            for o in out:
                self._check_pull_out(keys[0], o)
                self._store[keys[0]].copyto(o)
            if t0 is not None:
                self._record_pull(keys[0], len(out))
                _TM_PULL_SEC.observe(time.perf_counter() - t0,
                                     store=self.type)
            return
        if not single:
            _check_pairs(keys, outs, "pull", what="out arrays")
        if (self._fused is not None and not single
                and self._fused.handle_pull(keys, outs)):
            return
        for k, o in zip(keys, outs):
            t0 = time.perf_counter() if _tm.enabled() else None
            if isinstance(o, (list, tuple)):
                for oo in o:
                    self._check_pull_out(k, oo)
                    self._store[k].copyto(oo)
                ncopies = len(o)
            else:
                self._check_pull_out(k, o)
                self._store[k].copyto(o)
                ncopies = 1
            if t0 is not None:
                self._record_pull(k, ncopies)
                _TM_PULL_SEC.observe(time.perf_counter() - t0,
                                     store=self.type)

    def _check_pull_out(self, k, oo):
        """A row-sparse out array can only receive a row-sparse stored
        value; silently densifying INTO a sparse holder (or scattering
        a dense value across one) is the wrong-answer class the stype
        checks exist to stop."""
        if getattr(oo, "stype", "default") == "row_sparse" \
                and getattr(self._store[k], "stype",
                            "default") == "default":
            raise MXNetError(
                f"KVStore.pull: key {k!r} holds a 'default' (dense) "
                "value but the out array is 'row_sparse'; use "
                "row_sparse_pull(key, row_ids=...) for row subsets")

    def _record_pull(self, k, ncopies):
        if _tm.enabled():
            _TM_PULL.inc(store=self.type)
            _TM_PULL_BYTES.inc(_nbytes(self._store[k]) * ncopies,
                               store=self.type)

    # -------------------------------------------------------------- optimizer
    def set_optimizer(self, optimizer):
        """Parity: kvstore.py set_optimizer — runs the optimizer inside the
        store (update_on_kvstore mode; server-side for dist).  When the
        optimizer exposes a fused rule, batched pushes route through the
        bucketed jit-fused update engine (kvstore_fused.py)."""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)
        self._maybe_init_fused()

    def _maybe_init_fused(self):
        if self._fused is not None:
            # the outgoing engine may hold sharded optimizer state only
            # it can map back to per-key NDArrays
            self._fused.ensure_host_state()
        self._fused = None
        if self._optimizer is None or (
                "dist" in self.type and not self._collective):
            # PS-transport dist stores keep the per-key RPC/priority
            # contract; COLLECTIVE dist_sync routes through the bucket
            # engine — the cross-host all-reduce, 1/N-per-host update
            # and param all-gather all happen in-trace (ISSUE 13)
            return
        from . import kvstore_fused as kvf

        if not kvf.fused_update_enabled():
            return
        if self._optimizer.fused_rule() is None:
            return  # no fused rule (NAG, centered RMSProp, ...) -> eager
        self._fused = kvf.FusedUpdateEngine(self, self._optimizer,
                                            self._updater)

    def _set_updater(self, updater):
        # a custom Python updater has no fused rule — eager per-key path
        if self._fused is not None:
            self._fused.ensure_host_state()
        self._updater = updater
        self._optimizer = None
        self._fused = None

    set_updater = _set_updater

    # ------------------------------------------------------------ distributed
    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    def barrier(self):
        pass

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        if self._fused is not None:
            # sharded flat state materializes into the per-key NDArrays
            # the pickled state dict is built from
            self._fused.ensure_host_state()
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def send_command_to_servers(self, head, body):
        pass

    def get_num_dead_node(self, node_id, timeout=60):
        """Parity: KVStore::get_num_dead_node (kvstore.h:242) — in-process
        stores have no remote nodes."""
        return 0


class _PSClient:
    """Worker-side parameter-server client (parity: the ps::KVWorker role
    of src/kvstore/kvstore_dist.h).  One TCP connection per server;
    big arrays are sliced evenly across ALL servers, small keys hash to
    one server (EncodeKey, kvstore_dist.h:264-302)."""

    def __init__(self, servers, rank=0):
        import itertools
        import socket
        import threading
        import time
        from concurrent.futures import ThreadPoolExecutor

        from . import kvstore_server as ps

        self._ps = ps
        self.rank = rank
        self._socks = []
        self._locks = []
        # request ids for idempotent retransmit: non-idempotent RPCs
        # (push/barrier/init/control) carry one so a retry after a
        # broken connection replays the server's cached reply instead
        # of re-applying (pid included — a recovered worker reuses its
        # rank but must not collide with its previous life's ids)
        self._rids = itertools.count(1)
        # persistent pool: one slot per server (matches the per-socket
        # locks) — spawning a pool per push/pull would dominate small RPCs
        self._pool = ThreadPoolExecutor(max_workers=max(len(servers), 1))

        for addr in servers:
            host, port = addr.rsplit(":", 1)
            # servers come up in parallel with workers (launch.py starts
            # them together); retry until the listener is bound
            deadline = time.monotonic() + 120
            while True:
                try:
                    s = socket.create_connection((host, int(port)), timeout=120)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.2)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # the timeout above applies to connect only: a sync-mode pull
            # or barrier legitimately parks server-side until the slowest
            # worker arrives, so reads must block indefinitely
            s.settimeout(None)
            self._socks.append(s)
            self._locks.append(threading.Lock())
        self.num_servers = len(servers)
        self.bigarray_bound = int(os.environ.get(
            "MXNET_KVSTORE_BIGARRAY_BOUND", str(1000 * 1000)))
        self._servers = list(servers)
        # heartbeat over DEDICATED connections: the request sockets can be
        # parked server-side for a whole sync round (legitimately), which
        # would starve liveness signals exactly when worker skew is worst
        # (parity: ps-lite's separate heartbeat channel to the scheduler)
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()

    def _heartbeat_loop(self):
        import socket
        import time

        interval = float(os.environ.get("MXTPU_PS_HEARTBEAT_S", "1.0"))
        socks = [None] * self.num_servers
        while not self._hb_stop.wait(interval):
            for i, addr in enumerate(self._servers):
                try:
                    if socks[i] is None:
                        host, port = addr.rsplit(":", 1)
                        socks[i] = socket.create_connection(
                            (host, int(port)), timeout=5)
                    self._ps.send_msg(socks[i], {"cmd": "heartbeat",
                                                 "rank": self.rank})
                    self._ps.recv_msg(socks[i])
                except OSError:
                    try:
                        if socks[i] is not None:
                            socks[i].close()
                    finally:
                        socks[i] = None
        for s in socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def dead_nodes(self, timeout):
        """Union of stale worker ranks across all servers (fresh
        connections — the request sockets may be parked)."""
        import socket

        dead = set()
        for addr in self._servers:
            try:
                host, port = addr.rsplit(":", 1)
                with socket.create_connection((host, int(port)),
                                              timeout=10) as s:
                    self._ps.send_msg(s, {"cmd": "dead_nodes",
                                          "timeout": timeout})
                    reply = self._ps.recv_msg(s)
                    if reply is not None:  # None = clean EOF mid-shutdown
                        dead.update(reply.get("dead", []))
            except OSError:
                continue
        return sorted(dead)

    _MUTATING_CMDS = ("init", "push", "barrier", "control")

    def _connect_server(self, server, timeout=5.0):
        import socket

        host, port = self._servers[server].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(None)  # reads may legitimately park (sync mode)
        return s

    def _drop_sock(self, server):
        s = self._socks[server]
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
        self._socks[server] = None

    def rpc(self, server, msg, retries=None):
        """One RPC with bounded retry: a transport failure (broken
        pipe, reset, truncated reply, injected ``dist_send``/
        ``dist_recv`` drop) closes the socket, backs off exponentially
        with jitter (``MXTPU_DIST_BACKOFF_MS``), reconnects, and
        retransmits — idempotently, via the request id the server
        dedupes on.  After ``MXTPU_DIST_RETRIES`` retries the failure
        surfaces as an MXNetError naming the peer and attempt count
        (callers add the key) instead of a raw socket.error."""
        import random as _random_mod
        import socket
        import time

        from . import faults as _faults

        if msg.get("cmd") in self._MUTATING_CMDS and "rid" not in msg:
            msg["rid"] = f"{self.rank}:{os.getpid()}:{next(self._rids)}"
        max_attempts = (dist_retries() if retries is None
                        else max(int(retries), 0)) + 1
        delay = dist_backoff_ms() / 1000.0
        last_exc = None
        for attempt in range(1, max_attempts + 1):
            try:
                with self._locks[server]:
                    if self._socks[server] is None:
                        self._socks[server] = self._connect_server(server)
                    sock = self._socks[server]
                    try:
                        if _faults.should_drop("dist_send"):
                            raise OSError("injected dist_send drop")
                        self._ps.send_msg(sock, msg)
                        if _faults.should_drop("dist_recv"):
                            raise OSError("injected dist_recv drop")
                        reply = self._ps.recv_msg(sock)
                        if reply is None:
                            raise OSError("connection closed by peer")
                    except (OSError, socket.timeout):
                        # the stream may hold a half-sent request or an
                        # unread reply: never reuse it
                        self._drop_sock(server)
                        raise
                return reply
            except (OSError, socket.timeout) as exc:
                last_exc = exc
                if attempt >= max_attempts:
                    break
                if _tm.enabled():
                    _TM_DIST_RETRY.inc(op=str(msg.get("cmd", "?")))
                time.sleep(delay * (0.5 + _random_mod.random()))
                delay = min(delay * 2.0, 5.0)
        from . import telemetry as _tm_mod

        dump = _tm_mod.health.auto_dump("fault")
        raise MXNetError(
            f"KVStoreDist RPC {msg.get('cmd')!r} to server "
            f"{self._servers[server]} failed after {max_attempts} "
            f"attempt(s): {last_exc!r}"
            + (f" (flight record: {dump})" if dump else "")
        ) from last_exc

    def rpc_all(self, msg):
        return list(self._pool.map(lambda i: self.rpc(i, dict(msg)),
                                   range(self.num_servers)))

    # -- key encoding -----------------------------------------------------
    def _assignment(self, key, size):
        """Returns [(server, part_key, flat_slice)] for one logical key."""
        if size < self.bigarray_bound or self.num_servers == 1:
            # deterministic across processes (Python's hash() is salted):
            # parity with EncodeKey's stable key->server map
            # (kvstore_dist.h:264-302)
            import zlib

            server = zlib.crc32(str(key).encode()) % self.num_servers
            return [(server, str(key), slice(0, size))]
        bounds = np.linspace(0, size, self.num_servers + 1).astype(np.int64)
        return [(i, f"{key}#p{i}", slice(int(bounds[i]), int(bounds[i + 1])))
                for i in range(self.num_servers)]

    def init(self, key, value: np.ndarray):
        flat = value.reshape(-1)
        for server, pkey, sl in self._assignment(key, flat.size):
            self.rpc(server, {"cmd": "init", "key": pkey, "value": flat[sl]})

    def push(self, key, value: np.ndarray):
        flat = np.ascontiguousarray(value).reshape(-1)
        parts = self._assignment(key, flat.size)
        if len(parts) == 1:
            server, pkey, sl = parts[0]
            self.rpc(server, {"cmd": "push", "key": pkey, "value": flat[sl],
                              "rank": self.rank})
            return
        list(self._pool.map(
            lambda p: self.rpc(p[0], {"cmd": "push", "key": p[1],
                                      "value": flat[p[2]],
                                      "rank": self.rank}), parts))

    def pull(self, key, shape, dtype):
        size = int(np.prod(shape))
        parts = self._assignment(key, size)
        out = np.empty(size, dtype=dtype)
        if len(parts) == 1:
            server, pkey, sl = parts[0]
            out[sl] = self.rpc(server, {"cmd": "pull", "key": pkey,
                                        "rank": self.rank})["value"]
        else:
            def fetch(p):
                out[p[2]] = self.rpc(p[0], {"cmd": "pull", "key": p[1],
                                            "rank": self.rank})["value"]

            list(self._pool.map(fetch, parts))
        return out.reshape(shape)

    def barrier(self):
        self.rpc(0, {"cmd": "barrier"})

    def control(self, head, body=None):
        self.rpc_all({"cmd": "control", "head": head, "body": body})

    def control_sequential(self, head, body=None):
        """Deliver a control message to every server WITHOUT the thread
        pool.  atexit handlers run after threading._shutdown has joined
        executor workers, so pool.map there raises 'cannot schedule new
        futures after interpreter shutdown' and the message is lost —
        the shutdown path must use the still-open sockets directly.
        Returns [(server, exception)] for servers that could not be
        reached."""
        errors = []
        for i in range(self.num_servers):
            try:
                # a hung-but-alive server must not block process exit:
                # bound the shutdown RPC (normal RPCs block indefinitely
                # by design — sync-mode pulls park server-side) and skip
                # the retry/backoff ladder (retries=0): at exit a dead
                # server is reported, not courted
                if self._socks[i] is not None:
                    self._socks[i].settimeout(5.0)
                self.rpc(i, {"cmd": "control", "head": head,
                             "body": body}, retries=0)
            except Exception as exc:  # noqa: BLE001 — collected, not hidden
                errors.append((i, exc))
        return errors

    def close(self):
        self._hb_stop.set()
        self._pool.shutdown(wait=False)
        for s in self._socks:
            if s is None:  # dropped by the retry path, never reopened
                continue
            try:
                s.close()
            except OSError:
                pass


class KVStoreDist(KVStore):
    """Multi-process kvstore (parity: src/kvstore/kvstore_dist.h — the
    ps-lite worker client).

    Two transports, chosen by launch context:

    - **Parameter server** (``MXTPU_PS_SERVERS`` set by tools/launch.py):
      real multi-process PS with sync/async modes — the reference's
      dist_sync / dist_async semantics over host TCP, including
      server-side optimizers and big-array sharding across servers.
    - **jax.distributed** (TPU pods): sync aggregation should instead
      ride DCN/ICI collectives inside the training step (parallel/,
      FusedTrainer) — the PS is only needed for async semantics.
      Single-process runs degrade to local semantics with rank 0/size 1,
      matching the reference launched without a tracker.
    """

    def __init__(self, kv_type):
        super().__init__(kv_type)
        self._rank = int(os.environ.get("MXTPU_RANK",
                                        os.environ.get("DMLC_RANK", "0")))
        self._size = int(os.environ.get("MXTPU_NUM_WORKERS",
                                        os.environ.get("DMLC_NUM_WORKER", "1")))
        # restart-after-crash flag (parity: kvstore_dist.h:35-39 — a
        # recovered worker must NOT re-init or re-barrier: the servers
        # already hold the model and the surviving workers are mid-epoch)
        self._recovery = os.environ.get(
            "MXTPU_KV_RECOVERY", os.environ.get("DMLC_RECOVERY", "")) == "1"
        self._shapes = {}
        self._client = None
        # Comm/compute overlap (the SURVEY §3.4 contract: per-key comm
        # scheduled as soon as its grad is ready, overlapping the rest of
        # backward): push/pull RPCs run as priority-ordered tasks on the
        # native host engine (src/engine.cc), one engine var per key so
        # a key's pull serializes after its own push.  Pulls resolve
        # lazily — the out array's next read waits (_Chunk.host_waiter).
        # MXTPU_PS_ASYNC=0 or MXNET_ENGINE_TYPE=NaiveEngine forces the
        # synchronous path.
        self._engine = None
        self._key_vars = {}
        servers = os.environ.get("MXTPU_PS_SERVERS", "")
        if not servers:
            # COLLECTIVE transport (ISSUE 13): no parameter server — sync
            # aggregation rides DCN/ICI collectives over the fused
            # sharded buckets on the process-spanning mesh.  Initialize
            # jax.distributed from the launcher env (validated) so
            # process_mesh() spans hosts, and take rank/size from the
            # live runtime.  dist_async still needs the PS for its
            # no-barrier semantics — without servers it degrades to
            # local update semantics like the reference without a
            # tracker (rank 0 / size 1 when single-process).
            from .parallel import dist as _dist

            self._collective = "async" not in kv_type
            if _dist.is_multi_host():
                _dist.init_from_env()
                import jax

                self._rank = jax.process_index()
                self._size = jax.process_count()
        if servers:
            self._client = _PSClient(servers.split(","), rank=self._rank)
            if (os.environ.get("MXTPU_PS_ASYNC", "1") == "1"
                    and os.environ.get("MXNET_ENGINE_TYPE",
                                       "") != "NaiveEngine"):
                from ._native import NativeEngine, available

                if available():
                    self._engine = NativeEngine()
            if "async" not in kv_type and not self._recovery:
                if self._rank == 0:
                    from .kvstore_server import K_SYNC_MODE

                    self._client.control(K_SYNC_MODE)
                self._client.barrier()
            import atexit

            atexit.register(self._send_stop)

    def _var(self, key):
        v = self._key_vars.get(key)
        if v is None:
            v = self._engine.new_var()
            self._key_vars[key] = v
        return v

    def _wait_outstanding(self):
        if self._engine is not None:
            self._engine.wait_all()

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    # ------------------------------------------------------------------ ops
    @staticmethod
    def _named_comm_error(op, k, exc):
        """The actionable error contract (ISSUE-11): a dead peer must
        surface the KEY being moved, the peer address + attempt count
        (already in the transport error), and the flight-record dump —
        never a raw socket.error the operator has to strace."""
        return MXNetError(f"KVStoreDist.{op}: key {k!r}: {exc}")

    def init(self, key, value):
        if self._client is None:
            return super().init(key, value)
        keys, _ = _key_list(key)
        values = value if isinstance(value, (list, tuple)) else [value]
        _check_pairs(keys, values, "init")
        for k, v in zip(keys, values):
            self._shapes[k] = (v.shape, np.dtype(v.dtype))
            if self._rank == 0 and not self._recovery:
                try:
                    self._client.init(k, v.asnumpy())
                except (MXNetError, OSError) as exc:
                    raise self._named_comm_error("init", k, exc) from exc
        if not self._recovery:
            # a recovered worker skips the init barrier: the other workers
            # passed it long ago and will never arrive again
            self._client.barrier()

    def push(self, key, value, priority=0):
        if self._client is None:
            if self._collective and self._size > 1 and _tm.enabled():
                # dispatch-side payload accounting for the in-trace
                # cross-host grad all-reduce (host shape math only)
                from .parallel import dist as _dist

                vals = value if isinstance(key, (list, tuple)) else [value]
                _dist.count_allreduce_bytes(sum(
                    _nbytes(v[0] if isinstance(v, (list, tuple)) else v)
                    for v in vals))
            return super().push(key, value, priority)
        from . import faults as _faults

        _faults.maybe_fail("kv_push")
        keys, single = _key_list(key)
        values = [value] if single else value
        if not single:
            _check_pairs(keys, values, "push")
        for v in values:
            v0 = v[0] if isinstance(v, (list, tuple)) and v else v
            if getattr(v0, "stype", "default") == "row_sparse":
                # a dist push would densify through asnumpy AND run the
                # server's dense update (momentum/wd on every row) —
                # silently different math from the local lazy path
                raise MXNetError(
                    "row_sparse push is not supported on dist stores "
                    "yet; densify explicitly with .todense() to accept "
                    "dense (non-lazy) update semantics")
        for k, v in zip(keys, values):
            if isinstance(v, (list, tuple)):
                merged = v[0].copy()
                for other in v[1:]:
                    merged += other.as_in_context(merged.context)
            else:
                merged = v
            if k not in self._shapes:
                self._shapes[k] = (merged.shape, np.dtype(merged.dtype))
            if self._engine is None:
                t0 = time.perf_counter() if _tm.enabled() else None
                try:
                    self._client.push(k, merged.asnumpy())
                except (MXNetError, OSError) as exc:
                    raise self._named_comm_error("push", k, exc) from exc
                if t0 is not None:
                    _TM_PUSH.inc(store=self.type)
                    _TM_PUSH_BYTES.inc(_nbytes(merged), store=self.type)
                    _TM_PUSH_SEC.observe(time.perf_counter() - t0,
                                         store=self.type)
                continue
            # snapshot the immutable jax.Array NOW: the caller may mutate
            # the NDArray right after push() returns (zero the grad, next
            # backward), and reading lazily on the worker would send THAT.
            # _read also resolves any pending engine write on the value (a
            # just-pulled array) on this thread — a lazy read would have
            # the push task wait on its own var.  Neither blocks: the
            # device->host fetch is np.asarray on the worker.
            raw = merged._read()

            def _do_push(k=k, raw=raw):
                from . import profiler as _prof

                t0 = time.perf_counter() if _tm.enabled() else None
                with _prof.span(f"kvstore_push[{k}]", category="kvstore"):
                    # the device->host fetch happens HERE, on the engine
                    # worker — the caller thread never blocks on the RPC
                    try:
                        self._client.push(k, np.asarray(raw))
                    except (MXNetError, OSError) as exc:
                        raise self._named_comm_error("push", k,
                                                     exc) from exc
                if t0 is not None:
                    _TM_PUSH.inc(store=self.type)
                    _TM_PUSH_BYTES.inc(_nbytes(raw), store=self.type)
                    _TM_PUSH_SEC.observe(time.perf_counter() - t0,
                                         store=self.type)

            self._engine.push(_do_push, mutable_vars=[self._var(k)],
                              priority=priority)

    def pull(self, key, out=None, priority=0):
        if self._client is None:
            return super().pull(key, out, priority)
        from . import faults as _faults

        _faults.maybe_fail("kv_pull")
        keys, single = _key_list(key)
        outs = [out] if isinstance(out, NDArray) else out
        if single and isinstance(out, (list, tuple)):
            outs = [out]
        elif not single:
            _check_pairs(keys, outs, "pull", what="out arrays")
        for k, o in zip(keys, outs):
            shape, dtype = self._shapes[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            if self._engine is None:
                t0 = time.perf_counter() if _tm.enabled() else None
                try:
                    val = self._client.pull(k, shape, dtype)
                except (MXNetError, OSError) as exc:
                    raise self._named_comm_error("pull", k, exc) from exc
                for oo in targets:
                    oo._set(val)
                if t0 is not None:
                    _TM_PULL.inc(store=self.type)
                    _TM_PULL_BYTES.inc(_nbytes(val) * len(targets),
                                       store=self.type)
                    _TM_PULL_SEC.observe(time.perf_counter() - t0,
                                         store=self.type)
                continue

            def _do_pull(k=k, shape=shape, dtype=dtype, targets=targets):
                from . import profiler as _prof

                t0 = time.perf_counter() if _tm.enabled() else None
                with _prof.span(f"kvstore_pull[{k}]", category="kvstore"):
                    try:
                        val = self._client.pull(k, shape, dtype)
                    except (MXNetError, OSError) as exc:
                        raise self._named_comm_error("pull", k,
                                                     exc) from exc
                    for oo in targets:
                        oo._set(val, _from_engine=True)
                if t0 is not None:
                    _TM_PULL.inc(store=self.type)
                    _TM_PULL_BYTES.inc(_nbytes(val) * len(targets),
                                       store=self.type)
                    _TM_PULL_SEC.observe(time.perf_counter() - t0,
                                         store=self.type)

            eng = self._engine
            # each out chunk carries its own write-serialization var:
            # pulls of DIFFERENT keys into the same out array would
            # otherwise run under disjoint per-key vars and land in
            # nondeterministic order on the threaded engine
            ovars = []
            for oo in targets:
                if oo._chunk.engine_var is None:
                    oo._chunk.engine_var = eng.new_var()
                ovars.append(oo._chunk.engine_var)
            var = self._var(k)  # serializes after this key's pushes
            self._engine.push(_do_pull, mutable_vars=[var] + ovars,
                              priority=priority)
            for oo in targets:
                # WaitToRead: the next read of the out array blocks until
                # every scheduled write to it landed.  WaitForVar enqueues
                # a marker behind all ops on the chunk's var, so a single
                # waiter replaces any previous one — no chain to grow.
                oo._chunk.host_waiter = (
                    lambda eng=eng, ov=oo._chunk.engine_var:
                        eng.wait_for_var(ov))

    def set_optimizer(self, optimizer):
        if self._client is None:
            return super().set_optimizer(optimizer)
        if self._recovery:
            return  # servers already hold the optimizer from the first life
        # parity: worker 0 ships the optimizer to servers (kvstore.py
        # set_optimizer -> send_command_to_servers)
        if self._rank == 0:
            from .kvstore_server import K_SET_OPTIMIZER

            self._client.control(K_SET_OPTIMIZER, pickle.dumps(optimizer))
        self._client.barrier()

    def send_command_to_servers(self, head, body):
        if self._client is not None and self._rank == 0:
            self._client.control(head, body)

    def barrier(self):
        if self._client is not None:
            self._wait_outstanding()  # in-flight pushes precede the barrier
            self._client.barrier()
            return
        # with a live jax.distributed backend this is a cross-host sync
        # under the MXTPU_DIST_BARRIER_TIMEOUT_S watchdog — a dead peer
        # raises HostLostError instead of parking this worker forever
        import jax

        if jax.process_count() > 1:
            from .parallel import dist as _dist

            _dist.barrier()

    def get_num_dead_node(self, node_id, timeout=60):
        """Parity: KVStore::get_num_dead_node (kvstore_dist.h:151-160) —
        count of worker ranks whose heartbeats went stale.  node_id is
        accepted for signature parity; the TCP PS has a single worker
        group.  Collective stores ask the coordinator (lease-expiry
        deaths) when one is armed.  Either way the count lands on the
        ``kvstore_dead_workers`` gauge (and /healthz)."""
        if self._client is None:
            from .parallel import coordinator as _coord

            n = 0
            client = _coord.client_from_env()
            if client is not None:
                n = len(client.cluster().get("dead", []))
        else:
            n = len(self._client.dead_nodes(timeout))
        if _tm.enabled():
            _TM_DEAD_WORKERS.set(n)
        return n

    def _send_stop(self):
        if self._client is not None:
            try:
                self._wait_outstanding()
            except Exception as exc:  # noqa: BLE001 — still stop the servers
                logging.warning("kvstore: outstanding comm failed: %r", exc)
            client, self._client = self._client, None
            from .kvstore_server import K_STOP_SERVER

            # body = our rank: a cleanly-stopped worker must not be
            # mistaken for a dead one by the server's stop accounting
            for server, exc in client.control_sequential(K_STOP_SERVER,
                                                         client.rank):
                logging.warning("kvstore: failed to stop server %d: %r",
                                server, exc)
            client.close()


def create(name="local") -> KVStore:
    """Parity: mx.kv.create (kvstore.py:385) + type parsing
    (src/kvstore/kvstore.cc:17-45)."""
    if not isinstance(name, str):
        raise TypeError("name must be str")
    if "dist" in name:
        return KVStoreDist(name)
    if name in ("local", "device", "local_allreduce_cpu",
                "local_allreduce_device", "local_update_cpu"):
        return KVStore(name)
    raise MXNetError(f"unknown kvstore type {name}")
