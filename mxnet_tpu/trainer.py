"""FusedTrainer — whole-step compilation: forward+backward+optimizer in ONE
XLA computation with buffer donation.

This is the TPU-native performance path (SURVEY.md §7): where the reference
overlaps per-op engine dispatch with per-key kvstore push/pull
(threaded_engine_perdevice.cc + comm.h priority scheduling), XLA gets the
entire training step as a single program — fusion handles elementwise
chains, GSPMD inserts gradient all-reduces over the mesh, and latency
hiding replaces the engine's comm/compute overlap (all collectives are
scheduled inside one program rather than as separate engine ops).

Donation (`donate_argnums` on params/opt-state/aux) gives in-place
semantics — the functional analogue of the reference's in-place optimizer
updates + PlanMemory inplace sharing.

Mixed precision: dtype='bfloat16' keeps fp32 master weights and runs
compute in bf16 (MXU fast path); the reference's fp16 path is
test_dtype.py-style casting.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import random as _random
from . import telemetry as _tm
from .executor import _build_graph_fn
from .initializer import Uniform
from .base import MXNetError
from .ndarray import NDArray
from .optim_rules import (  # noqa: F401 — rules shared with kvstore_fused
    _RULES, _adam_rule, _rmsprop_rule, _sgd_rule,
)

# --- telemetry families (docs/telemetry.md).  The `loop` label separates
# the fused whole-step path from the Module fit loop. -----------------------
_TM_SAMPLES = _tm.counter(
    "trainer_samples_total", "training samples dispatched",
    labels=("loop",))
_TM_STEP_SEC = _tm.histogram(
    "trainer_step_seconds",
    "train-step dispatch wall time (async: device completion not "
    "included)", labels=("loop",))


# The pure per-tensor update rules (_sgd_rule/_adam_rule/_rmsprop_rule)
# live in optim_rules.py — they are shared with the kvstore's bucketed
# fused-update engine; `lr` arrives per-call (a traced scalar, so
# schedules don't recompile).


class FusedTrainer:
    """One-jit-call-per-step trainer over a Symbol.

    data parallel: pass a mesh (or n_devices) — inputs shard over 'data',
    params replicate, XLA all-reduces gradients.
    """

    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 optimizer="sgd", optimizer_params=None, mesh: Optional[Mesh] = None,
                 initializer=None, dtype=None, sharding_rules=(),
                 remat=None, fixed_param_names=(), clip_global_norm=None,
                 lr_scheduler=None):
        # rematerialization = the reference's MXNET_BACKWARD_DO_MIRROR
        # (recompute activations in backward, env_var.md:55-57) — on TPU
        # it is jax.checkpoint around the forward.  Default follows the
        # same env var for parity.
        if remat is None:
            remat = os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0") == "1"
        self.remat = bool(remat)
        self.symbol = symbol
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.mesh = mesh
        # dtype=None follows the process AMP policy (MXTPU_AMP=bf16 →
        # bf16 compute + the fp32 masters this trainer always keeps);
        # an explicit dtype still wins — "bf16 by default" is one env
        # flag for the FusedTrainer path too
        if dtype is None:
            from . import amp as _amp

            dtype = _amp.amp_dtype() or jnp.float32
        self.dtype = jnp.dtype(dtype)
        opt_params = dict(optimizer_params or {})
        opt_params.setdefault("lr", opt_params.pop("learning_rate", 0.01))
        # lr schedule (parity: lr_scheduler.py's role in optimizer.py):
        # callable(num_update) -> lr, evaluated on the host each step and
        # fed to the jitted step as a traced scalar — no recompilation
        self._base_lr = float(opt_params.pop("lr"))
        self._lr_scheduler = lr_scheduler
        if lr_scheduler is not None and hasattr(lr_scheduler, "base_lr"):
            lr_scheduler.base_lr = self._base_lr
        if optimizer not in _RULES:
            raise ValueError(f"FusedTrainer supports {sorted(_RULES)}; "
                             f"use Module for {optimizer}")
        self._init_state, self._update = _RULES[optimizer](opt_params)
        self._sharding_rules = tuple(sharding_rules)
        # params excluded from the vjp: XLA prunes their whole gradient
        # subgraph (Module parity: fixed_param_names; e.g. frozen trunks)
        if isinstance(fixed_param_names, str):
            fixed_param_names = (fixed_param_names,)
        self._fixed = frozenset(fixed_param_names)
        # global-norm gradient clipping (beyond the per-element
        # clip_gradient the optimizer kernels apply): rescale the WHOLE
        # gradient tree when ||g||_2 exceeds the threshold — the standard
        # transformer-training guard
        if clip_global_norm is not None and not float(clip_global_norm) > 0:
            raise ValueError("clip_global_norm must be > 0 (a negative "
                             "threshold would flip gradient signs; 0 would "
                             "silently disable clipping)")
        self._clip_global_norm = (None if clip_global_norm is None
                                  else float(clip_global_norm))
        self._initializer = initializer or Uniform(0.01)
        # per-param multipliers (reference parity: optimizer.py
        # set_lr_mult/set_wd_mult) — static per param, folding into the
        # compile.  Like set_wd_mult, params not named *_weight/*_gamma
        # (biases, norm betas) default to NO weight decay; explicit
        # __wd_mult__/__lr_mult__ Variable attrs override.
        self._lr_mult, self._wd_mult = {}, {}
        for name in symbol.list_arguments():
            if not (name.endswith("_weight") or name.endswith("_gamma")):
                self._wd_mult[name] = 0.0
        for name, attr in symbol.attr_dict().items():
            if "__lr_mult__" in attr:
                self._lr_mult[name] = float(attr["__lr_mult__"])
            if "__wd_mult__" in attr:
                self._wd_mult[name] = float(attr["__wd_mult__"])
        # platform-sensitive ops (FlashAttention) must lower for the mesh
        # this trainer will run on, NOT jax.default_backend(): with an
        # accelerator plugin registered, a CPU-device mesh (the multichip
        # dryrun, multi-process CPU workers) still sees backend "tpu"
        platform = None
        if mesh is not None:
            try:
                platform = next(iter(mesh.devices.flat)).platform
            except Exception:  # noqa: BLE001
                platform = None
        self._platform = platform
        # graph-rewrite pipeline (mxnet_tpu.passes; MXTPU_GRAPH_PASSES):
        # the EXECUTED graph is the rewritten one — fewer traced nodes
        # per step compile — while self.symbol stays the user-facing
        # interface (list_arguments/infer_shape/attr_dict all read the
        # original; passes never rename variables, so the name spaces
        # agree)
        from . import passes as _passes

        self._exec_symbol = _passes.apply_graph_passes(symbol)
        self._graph_fn = _build_graph_fn(self._exec_symbol,
                                         platform=platform)
        # conv weights stored physically HWIO (filled by init(); see
        # _discover_hwio_params) — logical OIHW at every API boundary
        self._hwio: frozenset = frozenset()
        self.params: Dict[str, jax.Array] = {}
        self.aux: Dict[str, jax.Array] = {}
        self.opt_state: Dict[str, tuple] = {}
        # mixed precision keeps a DONATED bf16 copy of the params carried
        # step-to-step: the forward reads it directly and the next copy is
        # written inside the optimizer update (where the f32 master is
        # already in registers), instead of re-reading the whole f32
        # master tree to re-cast it at the top of every step — on
        # ResNet-50 that re-cast alone is ~100MB/step of HBM traffic
        self._use_ccache = self.dtype != jnp.float32
        self._cparams: Dict[str, jax.Array] = {}
        self._step_fn = None
        self._step = 0
        # health-layer state (set for real by _build_step)
        self._sentinel = False
        self._sent_names: tuple = ()
        self._mem_recorded = False
        self._donated_bytes = None
        self._cost_recorded = False

    # ------------------------------------------------------------------ setup
    def init(self, **input_shapes):
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**input_shapes)
        arg_names = self.symbol.list_arguments()
        aux_names = self.symbol.list_auxiliary_states()
        inputs = set(self.data_names + self.label_names)
        repl = (NamedSharding(self.mesh, P()) if self.mesh is not None else None)
        from .parallel.mesh import shard_params

        for name, shape in zip(arg_names, arg_shapes):
            if name in inputs:
                continue
            arr = NDArray(jnp.zeros(shape, dtype=jnp.float32))
            self._initializer(name, arr)
            self.params[name] = arr._read()
        if self.mesh is not None:
            # tensor-parallel rules shard matching params; rest replicate
            self.params = shard_params(self.mesh, self.params, self._sharding_rules)
        # HWIO weight storage: initialize in logical OIHW (fan-in/out
        # correct for the initializer), then flip the stored layout to
        # what the NHWC convs consume — masters, momentum, and compute
        # cache all live HWIO, so the step has ZERO weight-relayout
        # traffic (the xprof A/B measured +1.2 ms/step of 'data
        # formatting' on ResNet-50 b32 with OIHW storage).
        self._hwio = self._discover_hwio_params(
            arg_names, arg_shapes, aux_names, aux_shapes)
        if self._hwio:
            self._graph_fn = _build_graph_fn(
                self._exec_symbol, platform=self._platform,
                hwio_params=self._hwio)
            for name in self._hwio:
                v = jnp.transpose(self.params[name], (2, 3, 1, 0))
                if self.mesh is not None:
                    v = jax.device_put(v, self.params[name].sharding)
                self.params[name] = v
        unknown = self._fixed - set(self.params)
        if unknown:
            raise MXNetError(f"fixed_param_names not in the model: "
                             f"{sorted(unknown)} (have "
                             f"{sorted(self.params)[:8]}...)")
        for name, raw in self.params.items():
            if name in self._fixed:
                continue
            self.opt_state[name] = tuple(
                jax.device_put(s, raw.sharding) if self.mesh is not None else s
                for s in self._init_state(raw)
            )
        for name, shape in zip(aux_names, aux_shapes):
            arr = NDArray(jnp.zeros(shape, dtype=jnp.float32))
            self._initializer(name, arr)
            raw = arr._read()
            if repl is not None:
                raw = jax.device_put(raw, repl)
            self.aux[name] = raw
        self._refresh_compute_cache()
        self._build_step()
        return self

    def _discover_hwio_params(self, arg_names, arg_shapes, aux_names,
                              aux_shapes):
        """Trace the graph abstractly and collect conv-weight variables
        consumed by NHWC convs; those get HWIO physical storage.  Params
        matched by a sharding rule are excluded (rule specs are written
        against logical OIHW axes).  MXTPU_HWIO_STORAGE=0 opts out."""
        from .executor import channels_last_default

        if (os.environ.get("MXTPU_HWIO_STORAGE", "1") == "0"
                or not channels_last_default()):
            return frozenset()
        report = {"conv_w": set(), "other": set()}
        # probe the REWRITTEN graph — HWIO safety is about how the
        # executed graph consumes each weight, not how the user wrote it
        probe = _build_graph_fn(self._exec_symbol, layout_report=report)
        args = {n: jax.ShapeDtypeStruct(tuple(s), jnp.float32)
                for n, s in zip(arg_names, arg_shapes)}
        aux = {n: jax.ShapeDtypeStruct(tuple(s), jnp.float32)
               for n, s in zip(aux_names, aux_shapes)}
        try:
            jax.eval_shape(lambda a, x, k: probe(a, x, k, True),
                           args, aux, jax.random.PRNGKey(0))
        except Exception:  # noqa: BLE001 — abstract trace unsupported
            return frozenset()  # (custom ops needing values): keep OIHW
        # HWIO-safe = consumed ONLY as NHWC conv weights (a tied second
        # use — in-graph weight norms, a sibling NCHW conv — would read
        # the transposed axes as OIHW) and not under a sharding rule
        # (rule specs name logical OIHW axes)
        return frozenset(
            n for n in report["conv_w"] - report["other"]
            if not any(r.matches(n) for r in self._sharding_rules))

    def _logical_param(self, name, v):
        """Stored -> logical layout (HWIO conv weights back to OIHW)."""
        return jnp.transpose(v, (3, 2, 0, 1)) if name in self._hwio else v

    def _refresh_compute_cache(self):
        """(Re)build the carried compute-dtype param copy from the f32
        masters.  Call after any direct overwrite of ``self.params``
        outside step() (init/load_checkpoint do it for you)."""
        if not self._use_ccache:
            return
        dtype = self.dtype
        self._cparams = jax.jit(
            lambda p: {k: v.astype(dtype) if v.dtype == jnp.float32 else v
                       for k, v in p.items()})(self.params)

    def _build_step(self):
        graph_fn = self._graph_fn
        update = self._update
        dtype = self.dtype
        data_names = self.data_names
        label_names = self.label_names

        fixed = self._fixed
        use_ccache = self._use_ccache
        # numerics sentinel (MXTPU_SENTINEL, sampled at build): the step
        # ALSO returns a per-param isfinite mask + the global grad norm,
        # computed inside the same compiled program — zero extra
        # dispatches, synced only at reporting boundaries
        sentinel = _tm.health.sentinel_mode() is not None
        self._sentinel = sentinel
        self._sent_names = tuple(k for k in self.params if k not in fixed)
        sent_names = self._sent_names
        self._mem_recorded = False
        self._donated_bytes = None
        self._cost_recorded = False

        def train_step(params, cparams, aux, opt_state, batch, key, step, lr):
            # the per-step RNG fold happens INSIDE the compiled step (step
            # arrives as a traced scalar): an eager fold_in per step() call
            # would be one extra host->device dispatch on the hot path
            key = jax.random.fold_in(key, step)
            if use_ccache:
                compute_params = cparams
            else:
                compute_params = {
                    k: v.astype(dtype) if v.dtype == jnp.float32 else v
                    for k, v in params.items()
                }
            compute_aux = {k: v.astype(dtype) for k, v in aux.items()}
            args = dict(compute_params)
            for k in data_names:
                args[k] = batch[k].astype(dtype)
            for k in label_names:
                args[k] = batch[k]

            def fwd(p):
                a = dict(args)
                a.update(p)
                outs, new_aux = graph_fn(a, compute_aux, key, True)
                # master aux stays fp32
                new_aux = {k: v.astype(jnp.float32) for k, v in new_aux.items()}
                return outs, new_aux

            if self.remat:
                fwd = jax.checkpoint(fwd)
            trainable = {k: v for k, v in compute_params.items()
                         if k not in fixed}
            (outs, new_aux), vjp_fn = jax.vjp(fwd, trainable)
            head = [jnp.ones(o.shape, o.dtype) for o in outs]
            aux_cot = jax.tree_util.tree_map(jnp.zeros_like, new_aux)
            (grads,) = vjp_fn((head, aux_cot))

            f32_grads = {k: grads[k].astype(jnp.float32)
                         for k in params if k not in fixed}
            if sentinel:
                # raw (pre-clip) grads: a finite clip rescale cannot
                # mask an inf/nan, and the norm is the divergence
                # signal.  Flags + norm pack into ONE output leaf —
                # the extra dispatch cost is one tiny array
                fin_vec = jnp.stack([jnp.isfinite(f32_grads[k]).all()
                                     for k in sent_names]).astype(
                                         jnp.float32)
                gnorm_s = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                       for g in f32_grads.values()))
                sent_vec = jnp.concatenate([fin_vec, gnorm_s[None]])
            if self._clip_global_norm is not None:
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                     for g in f32_grads.values()))
                scale = jnp.minimum(1.0, self._clip_global_norm
                                    / jnp.maximum(gnorm, 1e-12))
                f32_grads = {k: g * scale for k, g in f32_grads.items()}

            new_params = {}
            new_cparams = {}
            new_opt = {}
            for k, w in params.items():
                if k in fixed:
                    new_params[k] = w
                    if use_ccache:
                        new_cparams[k] = cparams[k]
                    continue
                nw, ns = update(w, f32_grads[k], opt_state[k],
                                lr * self._lr_mult.get(k, 1.0),
                                self._wd_mult.get(k, 1.0))
                new_params[k] = nw
                if use_ccache:
                    new_cparams[k] = (nw.astype(dtype)
                                      if nw.dtype == jnp.float32 else nw)
                new_opt[k] = ns
            if sentinel:
                return (new_params, new_cparams, new_aux, new_opt, outs,
                        sent_vec)
            return new_params, new_cparams, new_aux, new_opt, outs

        self._step_fn = jax.jit(train_step, donate_argnums=(0, 1, 2, 3))

        def multi_step(params, cparams, aux, opt_state, stacked, key,
                       step0, lrs):
            # k steps in ONE dispatch: scan over the leading steps axis.
            # Per-step semantics (RNG fold by absolute step index, lr from
            # the host-computed schedule) are identical to train_step, so
            # step() and step_multi() are interchangeable mid-run.
            # Inputs arrive either pre-stacked ``(k, B, ...)`` or as
            # k-tuples of per-step ``(B, ...)`` arrays (the device-side
            # feed from DevicePrefetchIter batches) — tuples are stacked
            # HERE, inside the compiled program, so the caller never pays
            # a separate host-dispatched stack for data already on device.
            stacked = {k_: jnp.stack(v) if isinstance(v, tuple) else v
                       for k_, v in stacked.items()}
            k = lrs.shape[0]
            idxs = step0 + 1 + jnp.arange(k, dtype=jnp.int32)

            def body(carry, xs):
                p, cp, a, o = carry
                batch, idx, lr = xs
                res = train_step(p, cp, a, o, batch, key, idx, lr)
                if sentinel:
                    p, cp, a, o, outs, sent = res
                    return (p, cp, a, o), (outs, sent)
                p, cp, a, o, outs = res
                return (p, cp, a, o), outs

            (params, cparams, aux, opt_state), ys = jax.lax.scan(
                body, (params, cparams, aux, opt_state),
                (stacked, idxs, lrs))
            if sentinel:
                outs, sents = ys
                # sents is (k, n_params+1): row i flags step step0+1+i,
                # last column is that step's grad norm
                return params, cparams, aux, opt_state, outs, sents
            return params, cparams, aux, opt_state, ys

        self._multi_fn = jax.jit(multi_step, donate_argnums=(0, 1, 2, 3))
        # variant that ALSO donates the stacked batch (argnum 4): the
        # scan consumes the batch exactly once, so when nobody else holds
        # it XLA reuses its HBM instead of carrying a dead (k, B, ...)
        # buffer across the whole k-step program
        self._multi_fn_donate = jax.jit(multi_step,
                                        donate_argnums=(0, 1, 2, 3, 4))

        def eval_step(params, cparams, aux, batch, key):
            if use_ccache:
                compute_params = cparams
            else:
                compute_params = {
                    k: v.astype(dtype) if v.dtype == jnp.float32 else v
                    for k, v in params.items()
                }
            compute_aux = {k: v.astype(dtype) for k, v in aux.items()}
            args = dict(compute_params)
            for k in data_names:
                args[k] = batch[k].astype(dtype)
            for k in label_names:
                if k in batch:
                    args[k] = batch[k]
                else:
                    args[k] = jnp.zeros((batch[data_names[0]].shape[0],), jnp.float32)
            outs, _ = graph_fn(args, compute_aux, key, False)
            return outs

        self._eval_fn = jax.jit(eval_step)

    # ---------------------------------------------------------------- running
    def _mesh_spans_hosts(self) -> bool:
        """True when this trainer's mesh includes another process's
        devices (the multi-host collective path, docs/multihost.md)."""
        if self.mesh is None:
            return False
        me = jax.process_index()
        return any(d.process_index != me for d in self.mesh.devices.flat)

    def _place_global(self, raw, sharding):
        """Place one batch array onto the mesh.  Single-host meshes take
        the plain transfer; a mesh spanning other processes cannot
        ``device_put`` a committed local array (non-addressable
        devices), so each process contributes its ADDRESSABLE shards of
        the replicated global batch via make_array_from_callback — the
        canonical multi-host feed (every host constructs the same
        global batch; XLA sees one sharded array)."""
        if not self._mesh_spans_hosts():
            return jax.device_put(raw, sharding)
        host = np.asarray(raw)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx, _h=host: _h[idx])

    def _shard_batch(self, batch):
        out = {}
        for k, v in batch.items():
            if isinstance(v, NDArray):
                raw = v._read()
            elif isinstance(v, jax.Array):
                raw = v  # already on device — never round-trip to host
            else:
                raw = jnp.asarray(np.asarray(v))
            if self.mesh is not None:
                out[k] = self._place_global(raw, NamedSharding(
                    self.mesh, P("data", *([None] * (raw.ndim - 1)))))
            else:
                out[k] = raw
        return out

    def current_lr(self):
        """The learning rate the NEXT step will apply."""
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler(self._step + 1))
        return self._base_lr

    def step(self, **batch):
        """Run one fused train step; returns outputs (list of jax arrays)."""
        import time as _time

        lr = np.float32(self.current_lr())  # single source of lr truth
        self._step += 1
        perf_on = _tm.perf.enabled()
        t0 = _time.perf_counter() if (_tm.enabled() or perf_on) else None
        sb = self._shard_batch(batch)
        self._record_step_memory(sb)
        try:
            res = self._step_fn(
                self.params, self._cparams, self.aux, self.opt_state,
                sb, _random.current_key(),
                np.int32(self._step), lr)
        except Exception as e:  # noqa: BLE001 — OOM gets a report
            _tm.health.reraise_if_oom(e, site="trainer.step")
            raise
        if perf_on and not self._cost_recorded:
            # one-time analytical cost row for the fused step program
            # (telemetry/perf.py) — compile() is a cache lookup here,
            # the dispatch above already built the executable
            self._cost_recorded = True
            _tm.perf.attach_cost_analysis(
                f"fused_step[{self.symbol.name or 'graph'}]",
                self._step_fn, self.params, self._cparams, self.aux,
                self.opt_state, sb, _random.current_key(),
                np.int32(self._step), lr)
        if self._sentinel:
            (self.params, self._cparams, self.aux, self.opt_state,
             outs, sent) = res
            _tm.health.sentinel_record(site="fused_step", step=self._step,
                                       names=self._sent_names,
                                       finite=sent, packed_norm=True)
        else:
            (self.params, self._cparams, self.aux, self.opt_state,
             outs) = res
        if t0 is not None:
            _TM_STEP_SEC.observe(_time.perf_counter() - t0, loop="fused")
            _TM_SAMPLES.inc(next(iter(sb.values())).shape[0], loop="fused")
            _tm.health.donation_saved(self._donated_bytes or 0,
                                      site="trainer_step")
            if perf_on:
                _tm.perf.record_dispatch(
                    f"fused_step[{self.symbol.name or 'graph'}]",
                    _time.perf_counter() - t0)
        return outs

    def _tree_nbytes(self, *trees):
        total = 0
        for tree in trees:
            for leaf in jax.tree_util.tree_leaves(tree):
                try:
                    total += int(leaf.size) * np.dtype(leaf.dtype).itemsize
                except Exception:  # noqa: BLE001
                    pass
        return total

    def _record_step_memory(self, sb):
        """First-dispatch memory attribution for the fused step: the
        donated param/state trees alias their outputs (XLA reuses the
        HBM), so peak ~ arguments + batch.  Shape math; accelerator
        backends get the compiled memory_analysis upgrade through the
        executor-bound programs."""
        if self._mem_recorded:
            return
        self._mem_recorded = True
        try:
            donated = self._tree_nbytes(self.params, self._cparams,
                                        self.aux, self.opt_state)
            self._donated_bytes = donated
            batch_b = self._tree_nbytes(sb)
            label = f"fused_step[{self.symbol.name or 'graph'}]"
            _tm.health.record_program(label, argument=donated + batch_b,
                                      output=donated, alias=donated,
                                      source="shape_math")
        except Exception:  # noqa: BLE001 — accounting must never break step
            pass

    def step_multi(self, _donate=None, **stacked):
        """Run k fused train steps in ONE dispatch.

        Every value carries a leading steps axis — either pre-stacked
        ``(k, B, ...)`` where a step() input would be ``(B, ...)``, or a
        k-list/tuple of per-step ``(B, ...)`` arrays (e.g. batches from
        ``DevicePrefetchIter`` via ``io.step_multi_feeds``), which the
        compiled program stacks ON DEVICE — no host re-stacking, no extra
        dispatch.  One compiled lax.scan executes the k steps back to
        back, so the per-call host/dispatch cost — the dominant term for
        small batches on high-latency links (tools/probe_gap.py measured
        it at 82% of a b32 ResNet-50 step over the bench tunnel) — is
        paid once per k steps instead of once per step.  Interchangeable
        with step(): same per-step RNG folds, same lr schedule, same
        optimizer updates.

        ``_donate`` controls batch-buffer donation: ``True`` hands the
        input buffers to XLA (single-use feeds — the iterator pipeline;
        the arrays are consumed), ``False`` preserves them (benchmarks
        replaying one stack), ``None`` (default) donates exactly when
        every input was a host array — the device buffer was created
        here, so nobody else can hold it.

        Returns the per-step outputs stacked on axis 0, still lazy
        (async futures) — reading/blocking is the caller's sync point."""
        sb = {}
        owned = True
        for k_, v in stacked.items():
            if isinstance(v, (list, tuple)):
                # per-step device feed: keep the tuple structure; the jit
                # stacks in-trace
                if any(isinstance(e, (NDArray, jax.Array)) for e in v):
                    owned = False  # caller may still hold these buffers
                sb[k_] = tuple(
                    e._read() if isinstance(e, NDArray)
                    else (e if isinstance(e, jax.Array)
                          else jnp.asarray(np.asarray(e)))
                    for e in v)
                if self.mesh is not None:
                    sh = NamedSharding(self.mesh, P(
                        "data", *([None] * (sb[k_][0].ndim - 1))))
                    sb[k_] = tuple(self._place_global(e, sh)
                                   for e in sb[k_])
                continue
            if isinstance(v, NDArray):
                raw = v._read()
                owned = False
            elif isinstance(v, jax.Array):
                raw = v
                owned = False
            else:
                raw = jnp.asarray(np.asarray(v))
            if self.mesh is not None:
                # axis 0 is steps — the data-parallel shard axis is 1
                sb[k_] = self._place_global(raw, NamedSharding(
                    self.mesh, P(None, "data", *([None] * (raw.ndim - 2)))))
            else:
                sb[k_] = raw
        first = next(iter(sb.values()))
        k = len(first) if isinstance(first, tuple) else first.shape[0]
        if self._lr_scheduler is not None:
            lrs = np.asarray([self._lr_scheduler(self._step + 1 + i)
                              for i in range(k)], np.float32)
        else:
            lrs = np.full((k,), self._base_lr, np.float32)
        step0 = np.int32(self._step)
        self._step += k
        import time as _time

        donate = owned if _donate is None else bool(_donate)
        fn = self._multi_fn_donate if donate else self._multi_fn
        t0 = _time.perf_counter() if _tm.enabled() else None
        import warnings as _warnings

        self._record_step_memory(sb)
        with _warnings.catch_warnings():
            if donate:
                # batch donation is best-effort: when no output aliases
                # the batch (or the platform can't donate) jax warns per
                # call — the fallback is exactly the non-donated behavior
                _warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
            try:
                res = fn(
                    self.params, self._cparams, self.aux, self.opt_state,
                    sb, _random.current_key(), step0, lrs)
            except Exception as e:  # noqa: BLE001 — OOM gets a report
                _tm.health.reraise_if_oom(e, site="trainer.step_multi")
                raise
        if self._sentinel:
            (self.params, self._cparams, self.aux, self.opt_state,
             outs, sents) = res
            # sents rows map to steps step0+1 .. step0+k
            _tm.health.sentinel_record(site="fused_step_multi",
                                       step=int(step0) + 1,
                                       names=self._sent_names,
                                       finite=sents, packed_norm=True)
        else:
            (self.params, self._cparams, self.aux, self.opt_state,
             outs) = res
        if t0 is not None:
            _TM_STEP_SEC.observe(_time.perf_counter() - t0, loop="fused")
            per_step = (first[0].shape[0] if isinstance(first, tuple)
                        else first.shape[1])
            _TM_SAMPLES.inc(int(k * per_step), loop="fused")
            donated_b = self._donated_bytes or 0
            if donate:
                donated_b += self._tree_nbytes(sb)
            _tm.health.donation_saved(donated_b, site="trainer_step_multi")
        return outs

    def eval(self, **batch):
        key = jax.random.fold_in(_random.current_key(), 0)
        return self._eval_fn(self.params, self._cparams, self.aux,
                             self._shard_batch(batch), key)

    def get_params(self):
        return ({k: NDArray(self._logical_param(k, v))
                 for k, v in self.params.items()},
                {k: NDArray(v) for k, v in self.aux.items()})

    # ------------------------------------------------------------------- fit
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            validation_metric=None, num_epoch=1, batch_end_callback=None,
            epoch_end_callback=None, logger=None, checkpoint=None,
            resume=None):
        """Module.fit-shaped loop on the fused step (the whole-step-
        compiled perf path): per-batch metric updates, Speedometer-style
        callbacks, per-epoch eval — without hand-rolling the loop.

        Calls init() from the first batch's shapes if needed.  Returns
        self.  The metric sees the step's outputs (same contract as
        Module.update_metric).

        Survival layer (docs/fault_tolerance.md): ``checkpoint`` is a
        CheckpointManager or a directory (default: armed by
        ``MXTPU_CKPT_DIR`` + ``MXTPU_CKPT_EVERY``) — snapshots every N
        steps without draining the async window, saves a boundary
        checkpoint on SIGTERM (raising ``checkpoint.Preempted``), and a
        final one when training completes.  ``resume=True`` (or a
        path) restores the newest complete checkpoint — params,
        optimizer state, RNG, and the mid-epoch batch cursor — so a
        killed run continues step-exact."""
        import logging as _logging

        from . import metric as metric_mod
        from .module.base_module import BatchEndParam, _as_list

        log = logger or _logging.getLogger()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            if validation_metric is None and eval_data is not None:
                validation_metric = metric_mod.create(eval_metric)
            eval_metric = metric_mod.create(eval_metric)
        if validation_metric is not None and not isinstance(
                validation_metric, metric_mod.EvalMetric):
            validation_metric = metric_mod.create(validation_metric)
        if eval_data is not None and validation_metric is None:
            raise ValueError(
                "pass validation_metric when eval_metric is a metric "
                "instance (instances hold state; eval needs its own)")
        import time as _time

        train_names = ([d[0] for d in train_data.provide_data]
                       + [l[0] for l in train_data.provide_label])
        # feed the eval iterator's REAL labels when it provides them: the
        # zeros placeholder in eval_step only works for shape-agnostic
        # label consumers (e.g. Reshape(-1) loss heads); a fixed-shape
        # label consumer would fail or mis-trace with it
        eval_label_names = ([l[0] for l in getattr(eval_data, "provide_label",
                                                   None) or []]
                            if eval_data is not None else [])
        eval_names = ([d[0] for d in eval_data.provide_data]
                      + eval_label_names if eval_data is not None else None)
        from . import engine as _engine
        from . import checkpoint as _ckpt

        if isinstance(checkpoint, _ckpt.CheckpointManager):
            mgr = checkpoint
        elif checkpoint:
            mgr = _ckpt.CheckpointManager(str(checkpoint))
        else:
            mgr = _ckpt.CheckpointManager.from_env()
        start_epoch, resume_nbatch = 0, -1
        if resume not in (None, False):
            if not self.params:
                shapes = {d[0]: tuple(d[1]) for d in
                          list(train_data.provide_data)
                          + list(train_data.provide_label or [])}
                self.init(**shapes)
            path = (resume if isinstance(resume, str)
                    and os.path.exists(os.path.join(resume, _ckpt.MANIFEST))
                    else _ckpt.resolve_resume(resume, mgr))
            if path is None:
                log.warning("fit(resume=%r): no complete checkpoint "
                            "found; starting fresh", resume)
            else:
                meta = self.restore_state(path)
                if meta.get("epoch") is not None:
                    start_epoch = int(meta["epoch"])
                if meta.get("nbatch") is not None:
                    resume_nbatch = int(meta["nbatch"])
                log.info("resumed from %s (step %d, epoch %d, batch "
                         "cursor %d)", path, self._step, start_epoch,
                         resume_nbatch)
        if mgr is not None:
            mgr.install_preempt_handler()
        try:
            self._fit_impl(train_data, eval_data, eval_metric,
                           validation_metric, num_epoch,
                           batch_end_callback, epoch_end_callback, log,
                           train_names, eval_names, eval_label_names,
                           _engine, _time, mgr, start_epoch,
                           resume_nbatch)
            if mgr is not None and self.params:
                # terminal checkpoint: a resume of a finished run is a
                # no-op instead of a silent full retrain
                self.save_state(mgr, epoch=num_epoch, nbatch=-1,
                                background=False)
        except BaseException:
            # black box first, then crash: the ring + registry +
            # memory report of the dying run (MXTPU_FLIGHT_RECORD path)
            _tm.health.auto_dump("exception")
            raise
        finally:
            if mgr is not None:
                try:
                    mgr.wait()
                except Exception as exc:  # noqa: BLE001 — log, don't mask
                    log.warning("checkpoint writer failed: %r", exc)
                mgr.uninstall_preempt_handler()
        return self

    def _fit_impl(self, train_data, eval_data, eval_metric,
                  validation_metric, num_epoch, batch_end_callback,
                  epoch_end_callback, log, train_names, eval_names,
                  eval_label_names, _engine, _time, mgr=None,
                  start_epoch=0, resume_nbatch=-1):
        from . import checkpoint as _ckpt
        from .module.base_module import BatchEndParam, _as_list
        from .parallel import coordinator as _coordinator

        # elastic membership (docs/multihost.md): armed by
        # MXTPU_COORD_ADDR; step_poll is a pure host-side flag check
        coord = _coordinator.client_from_env()
        flight = _tm.health.flight_enabled()
        perf_on = _tm.perf.enabled()
        rec = flight or perf_on
        for epoch in range(start_epoch, num_epoch):
            tic = _time.time()
            eval_metric.reset()
            train_data.reset()
            # bounded in-flight window (MXTPU_ASYNC_DEPTH): step() and the
            # fused metric update are pure async dispatches, so this is
            # the only place the steady-state loop blocks
            window = _engine.AsyncWindow()
            prev_tick = None  # per-epoch: wall_s must not span eval/reset
            for nbatch, batch in enumerate(train_data):
                if epoch == start_epoch and nbatch <= resume_nbatch:
                    # mid-epoch resume: the checkpoint's cursor already
                    # trained these batches — replay the iterator past
                    # them so the step/RNG/schedule sequence lines up
                    continue
                feed = dict(zip(train_names,
                                list(batch.data) + list(batch.label)))
                if not self.params:
                    self.init(**{k: tuple(v.shape)
                                 for k, v in feed.items()})
                t0 = _time.perf_counter() if rec else 0.0
                outs = self.step(**feed)
                eval_metric.update(batch.label, [NDArray(o) for o in outs])
                tp = _time.perf_counter() if perf_on else 0.0
                window.push(list(outs))
                if rec:
                    # step-timing feed (ISSUE 14): wall_s = batch-to-
                    # batch host wall, reported by the coordinator
                    # heartbeat for straggler detection (host-side only)
                    now = _time.perf_counter()
                    if flight:
                        _tm.health.record_step(
                            loop="fused", step=self._step, epoch=epoch,
                            nbatch=nbatch, depth=len(window),
                            dispatch_s=now - t0,
                            wall_s=(now - prev_tick
                                    if prev_tick is not None else now - t0),
                            program=f"fused_step"
                                    f"[{self.symbol.name or 'graph'}]")
                    if perf_on:
                        # step decomposition (docs/perf_attr.md): the
                        # three buckets partition this step's wall by
                        # construction — data_wait is the iterator +
                        # inter-step host work, dispatch the async
                        # enqueues, window_stall the bounded-window
                        # backpressure inside push()
                        _tm.perf.record_step_buckets(
                            wall_s=(now - prev_tick
                                    if prev_tick is not None else now - t0),
                            data_wait=(max(t0 - prev_tick, 0.0)
                                       if prev_tick is not None else 0.0),
                            dispatch=tp - t0,
                            window_stall=now - tp)
                    prev_tick = now
                if coord is not None and coord.step_poll():
                    # membership changed: boundary checkpoint, then the
                    # named exit — the next generation resumes on the
                    # surviving mesh (re-bind via the checkpoint
                    # re-shard contract)
                    w = None
                    if mgr is not None:
                        w = self.save_state(mgr, epoch=epoch,
                                            nbatch=nbatch,
                                            background=False)
                    coord.raise_generation_changed(
                        getattr(w, "path", None))
                if mgr is not None:
                    if mgr.preempted:
                        # window boundary under preemption: capture is
                        # ordered behind the in-flight steps, written
                        # synchronously, then the run dies a named death
                        w = self.save_state(mgr, epoch=epoch,
                                            nbatch=nbatch,
                                            background=False)
                        raise _ckpt.Preempted(
                            "SIGTERM: checkpoint saved to "
                            f"{getattr(w, 'path', mgr.directory)!r}; "
                            "restart with fit(resume=True)")
                    if mgr.due(self._step):
                        self.save_state(mgr, epoch=epoch, nbatch=nbatch)
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=None)
                    for cb in _as_list(batch_end_callback):
                        cb(params)
            td0 = _time.perf_counter() if perf_on else 0.0
            window.drain()
            if perf_on:
                _tm.perf.record_bucket("boundary_sync",
                                       _time.perf_counter() - td0)
            for name, val in eval_metric.get_global_name_value():
                log.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            log.info("Epoch[%d] Time cost=%.3f", epoch,
                     _time.time() - tic)
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg, aux)
            if eval_data is not None:
                vm = validation_metric
                vm.reset()
                eval_data.reset()
                window = _engine.AsyncWindow()
                for batch in eval_data:
                    feed = dict(zip(eval_names,
                                    list(batch.data)
                                    + (list(batch.label)
                                       if eval_label_names else [])))
                    outs = self.eval(**feed)
                    vm.update(batch.label, [NDArray(o) for o in outs])
                    window.push(list(outs))
                window.drain()
                for name, val in vm.get_global_name_value():
                    log.info("Epoch[%d] Validation-%s=%f", epoch, name, val)
        return self

    def rebind_mesh(self, mesh: Optional[Mesh]):
        """Re-bind the step loop onto a new mesh shape (elastic shrink/
        grow — ISSUE 13): re-place params, aux, and optimizer state on
        the new mesh (XLA re-shards; the flat sharded kvstore state
        follows the same ``sync_shard_state`` contract on its next plan
        build) and recompile the step programs.  ``None`` collapses to
        single-device.  Training state is carried, not reset — step
        counter, RNG stream, and schedules continue; this is the
        in-process half of the generation restart (a restarted process
        gets the same effect from init() + restore_state())."""
        if not self.params:
            self.mesh = mesh
            return self
        self.mesh = mesh
        if mesh is not None:
            try:
                self._platform = next(iter(mesh.devices.flat)).platform
            except Exception:  # noqa: BLE001
                pass
            from .parallel.mesh import shard_params

            self.params = shard_params(mesh, self.params,
                                       self._sharding_rules)
            repl = NamedSharding(mesh, P())
            self.aux = {k: jax.device_put(v, repl)
                        for k, v in self.aux.items()}
            self.opt_state = {
                k: tuple(jax.device_put(s, self.params[k].sharding)
                         if s.ndim else jax.device_put(s, repl)
                         for s in v)
                for k, v in self.opt_state.items()}
        else:
            # collapse to the default device: host round-trip is the
            # portable way off an arbitrary sharding layout
            self.params = {k: jnp.asarray(np.asarray(v))
                           for k, v in self.params.items()}
            self.aux = {k: jnp.asarray(np.asarray(v))
                        for k, v in self.aux.items()}
            self.opt_state = {k: tuple(jnp.asarray(np.asarray(s))
                                       for s in v)
                              for k, v in self.opt_state.items()}
        self._refresh_compute_cache()
        self._build_step()
        return self

    # ------------------------------------------------------- survival layer
    def _checkpoint_arrays(self):
        """Device-resident snapshot set for the async checkpointer: the
        f32 masters, aux states, and every optimizer-state slot — the
        arrays the fused step owns (the bf16 compute cache is derived,
        never saved).  Values are live jax arrays; checkpoint.snapshot
        makes the detached device copies."""
        arrs = {}
        for k, v in self.params.items():
            arrs["param/" + k] = v
        for k, v in self.aux.items():
            arrs["aux/" + k] = v
        for k, slots in self.opt_state.items():
            for i, s in enumerate(slots):
                arrs[f"opt/{k}/{i}"] = s
        return arrs

    def _checkpoint_meta(self, epoch=None, nbatch=None):
        key = np.asarray(_random.current_key())
        return {
            "trainer": "fused",
            "step": int(self._step),
            "epoch": None if epoch is None else int(epoch),
            "nbatch": None if nbatch is None else int(nbatch),
            "signature": self._exec_symbol.structural_signature(),
            "hwio": sorted(self._hwio),
            "rng_key": key.tolist(),
            "rng_dtype": str(key.dtype),
        }

    def save_state(self, target, epoch=None, nbatch=None, background=True):
        """Write a resumable checkpoint (params + aux + optimizer state
        + step/epoch/batch cursor + RNG state) through the survival
        layer (checkpoint.py): device-side capture ordered after the
        in-flight steps — the AsyncWindow is NOT drained — with the
        fetch + file IO on a background writer.  ``target`` is a
        :class:`~mxnet_tpu.checkpoint.CheckpointManager` or a
        directory.  Returns the write handle (or None when the
        manager skipped an in-flight duplicate)."""
        from . import checkpoint as _ckpt

        if not self.params:
            raise MXNetError("save_state: trainer not initialized")
        meta = self._checkpoint_meta(epoch=epoch, nbatch=nbatch)
        arrays = self._checkpoint_arrays()
        if isinstance(target, _ckpt.CheckpointManager):
            return target.save(self._step, arrays, meta=meta,
                               background=background)
        return _ckpt.save(str(target), self._step, arrays, meta=meta,
                          background=background)

    def restore_state(self, source):
        """Restore from a survival-layer checkpoint into this
        INITIALIZED trainer: validates the manifest (checksums + the
        bound graph's structural signature), re-applies this trainer's
        shardings/layouts (the checkpoint may come from a different
        shard layout or HWIO config), and restores the step cursor and
        RNG stream for bit-parity resume.  ``source`` is a checkpoint
        path, a directory of checkpoints (newest complete wins), or a
        CheckpointManager.  Returns the checkpoint's meta dict."""
        import jax.numpy as jnp

        from . import checkpoint as _ckpt

        if not self.params:
            raise MXNetError("restore_state: call init() first (shapes/"
                             "shardings come from init)")
        if isinstance(source, _ckpt.CheckpointManager):
            path = source.latest()
        elif isinstance(source, str) and os.path.exists(
                os.path.join(source, _ckpt.MANIFEST)):
            path = source
        else:
            path = _ckpt.latest(str(source))
        if path is None:
            raise _ckpt.CheckpointError(
                f"no complete checkpoint found under {source!r}")
        arrays, manifest = _ckpt.load(path)
        meta = manifest.get("meta", {})
        sig = self._exec_symbol.structural_signature()
        saved_sig = meta.get("signature")
        if saved_sig is not None and saved_sig != sig:
            raise _ckpt.CheckpointError(
                f"checkpoint {path!r} was saved from a different graph "
                f"(signature {saved_sig[:16]}... vs bound "
                f"{sig[:16]}...); refusing to load mismatched weights")
        saved_hwio = set(meta.get("hwio", ()))

        def _relayout(k, host):
            # stored-layout translation between configs: the checkpoint
            # carries arrays in ITS stored layout and names the HWIO set
            if host.ndim != 4:
                return host
            if k in saved_hwio and k not in self._hwio:
                return np.transpose(host, (3, 2, 0, 1))
            if k not in saved_hwio and k in self._hwio:
                return np.transpose(host, (2, 3, 1, 0))
            return host

        def _put(host, like):
            raw = jnp.asarray(host)
            if raw.shape != like.shape:
                raise _ckpt.CheckpointError(
                    f"checkpoint {path!r}: shape {raw.shape} does not "
                    f"match the bound {tuple(like.shape)}")
            return (jax.device_put(raw, like.sharding)
                    if self.mesh is not None else raw)

        for k in self.params:
            name = "param/" + k
            if name not in arrays:
                raise _ckpt.CheckpointError(
                    f"checkpoint {path!r} lacks param {k!r}")
            self.params[k] = _put(_relayout(k, arrays[name]),
                                  self.params[k])
        for k in self.aux:
            name = "aux/" + k
            if name not in arrays:
                raise _ckpt.CheckpointError(
                    f"checkpoint {path!r} lacks aux state {k!r}")
            self.aux[k] = _put(arrays[name], self.aux[k])
        for k, slots in self.opt_state.items():
            new = []
            for i, s in enumerate(slots):
                name = f"opt/{k}/{i}"
                if name not in arrays:
                    raise _ckpt.CheckpointError(
                        f"checkpoint {path!r} lacks optimizer state "
                        f"{k}:{i} (different optimizer?)")
                host = arrays[name]
                if host.ndim == 4 and host.shape != tuple(s.shape):
                    host = _relayout(k, host)
                new.append(_put(host, s))
            self.opt_state[k] = tuple(new)
        if meta.get("step") is not None:
            self._step = int(meta["step"])
        if meta.get("rng_key") is not None:
            _random._state["key"] = jnp.asarray(np.array(
                meta["rng_key"],
                dtype=np.dtype(meta.get("rng_dtype", "uint32"))))
        self._refresh_compute_cache()
        if _tm.enabled():
            _ckpt._TM_RESUME.inc(status="ok")
        return meta

    # ------------------------------------------------------------ checkpoints
    def _gather(self, v):
        """Full host value of a (possibly sharded) array.  On multi-host
        meshes arrays span non-addressable devices, so gather across
        processes first."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            v = multihost_utils.process_allgather(v, tiled=True)
        return np.asarray(v)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        background=False):
        """Write ``prefix-symbol.json`` + ``prefix-%04d.params`` — the
        Module checkpoint format, loadable by Module/FeedForward — plus a
        FusedTrainer-format ``.states`` file (flat per-key slot arrays +
        the step counter; NOT Module's pickled-updater format) when
        ``save_optimizer_states``.

        ``background=True`` overlaps the checkpoint with training:
        params are immutable jax arrays, so snapshotting their refs (and
        the step counter) is free, and the device→host fetch + file
        write run on a writer thread while step() keeps training — on
        slow host links the fetch dominates checkpoint time, so this
        hides essentially all of it.  Returns a ``threading.Thread``
        (already started; ``join()`` before relying on the files);
        a raise on the writer thread is re-raised by ``join`` via the
        returned thread's ``exc`` attribute being checked in
        ``wait_checkpoint``."""
        if background and jax.process_count() > 1:
            # the writer thread's gather collectives would interleave
            # with training-step collectives in host-dependent order —
            # a deadlock class; multi-process saves stay synchronous
            import warnings

            warnings.warn("background checkpointing is single-process "
                          "only; saving synchronously", stacklevel=2)
            background = False

        if background:
            # SNAPSHOT at HBM speed: the fused step DONATES its buffers,
            # so bare refs would be invalidated by the next step() — a
            # device-side copy per tensor (dispatched async) detaches
            # the snapshot; only the slow device→host fetch runs on the
            # writer thread.  The synchronous path below reads the live
            # tensors directly (no duplicate HBM footprint).
            def snap(v):
                return jnp.copy(v) if isinstance(v, jax.Array) else v
        else:
            def snap(v):
                return v

        params = {k: snap(v) for k, v in self.params.items()}
        aux = {k: snap(v) for k, v in self.aux.items()}
        step = self._step
        opt_state = {k: [snap(s) for s in v]
                     for k, v in self.opt_state.items()} \
            if save_optimizer_states else None

        def _write():
            from . import ndarray as nd_mod
            from .model import save_checkpoint as _save

            # HWIO-stored conv weights leave in logical OIHW; the
            # transpose runs on HOST numpy so the writer thread never
            # dispatches device work against the training stream
            arg = {k: NDArray(np.transpose(self._gather(v), (3, 2, 0, 1))
                              if k in self._hwio else self._gather(v))
                   for k, v in params.items()}
            auxd = {k: NDArray(self._gather(v)) for k, v in aux.items()}
            _save(prefix, epoch, self.symbol, arg, auxd)
            if opt_state is not None:
                flat = {"__step__": NDArray(np.array([step], np.int64))}
                for k, states in opt_state.items():
                    for i, s in enumerate(states):
                        host = self._gather(s)
                        # slot arrays mirror their param's layout: HWIO-
                        # stored conv weights leave in logical OIHW so a
                        # .states file loads into ANY trainer config
                        # (MXTPU_HWIO_STORAGE=0, NCHW mode); shape-guard
                        # because some optimizers carry scalar slots
                        if (k in self._hwio and host.ndim == 4
                                and host.shape == params[k].shape):
                            host = np.transpose(host, (3, 2, 0, 1))
                        flat[f"{k}:{i}"] = NDArray(host)
                nd_mod.save("%s-%04d.states" % (prefix, epoch), flat)

        if not background:
            _write()
            return None
        import threading

        def _runner():
            try:
                _write()
            except BaseException as e:  # noqa: BLE001 — surfaced in join
                thread.exc = e

        thread = threading.Thread(target=_runner, daemon=False,
                                  name="ckpt-writer")
        thread.exc = None
        thread.start()
        return thread

    @staticmethod
    def wait_checkpoint(thread):
        """Join a background save and re-raise any writer-thread error."""
        if thread is None:
            return
        thread.join()
        if getattr(thread, "exc", None) is not None:
            raise thread.exc

    def load_checkpoint(self, prefix, epoch, load_optimizer_states=False):
        """Restore params/aux (and optimizer state + step counter) saved
        by save_checkpoint into this INITIALIZED trainer, re-applying the
        trainer's shardings.  Missing files or key mismatches raise —
        silently training on reset state is worse than failing."""
        from . import ndarray as nd_mod
        from .base import MXNetError
        from .model import load_checkpoint as _load

        if not self.params:
            raise MXNetError("load_checkpoint: call init() first (the "
                             "trainer's shapes/shardings come from init)")
        _, arg, aux = _load(prefix, epoch)
        missing = set(self.params) - set(arg)
        if missing:
            raise MXNetError(f"checkpoint {prefix!r} lacks params "
                             f"{sorted(missing)[:5]}...")
        missing_aux = set(self.aux) - set(aux)
        if missing_aux:
            # same contract as params: silently keeping init values for
            # e.g. BatchNorm moving stats is worse than failing
            raise MXNetError(f"checkpoint {prefix!r} lacks aux states "
                             f"{sorted(missing_aux)[:5]}...")
        for k, v in arg.items():
            if k in self.params:
                host = v.asnumpy()
                if k in self._hwio:  # checkpoints are logical OIHW
                    host = np.transpose(host, (2, 3, 1, 0))
                raw = jnp.asarray(host)
                self.params[k] = (jax.device_put(raw, self.params[k].sharding)
                                  if self.mesh is not None else raw)
        for k, v in aux.items():
            if k in self.aux:
                raw = jnp.asarray(v.asnumpy())
                self.aux[k] = (jax.device_put(raw, self.aux[k].sharding)
                               if self.mesh is not None else raw)
        if load_optimizer_states:
            spath = "%s-%04d.states" % (prefix, epoch)
            flat = nd_mod.load(spath)  # missing file raises, like Module
            step = flat.pop("__step__", None)
            if step is not None:
                self._step = int(step.asnumpy()[0])
            for k in list(self.opt_state):
                states = []
                for i in range(len(self.opt_state[k])):
                    arr = flat.get(f"{k}:{i}")
                    if arr is None:
                        raise MXNetError(
                            f"optimizer state {k}:{i} missing from {spath!r} "
                            "(different optimizer, or a truncated save?)")
                    host = arr.asnumpy()
                    # .states slots are logical OIHW on disk (save-side
                    # canonicalization); flip the ones mirroring an
                    # HWIO-stored param back to storage layout
                    stored = tuple(self.opt_state[k][i].shape)
                    if (k in self._hwio and host.ndim == 4
                            and tuple(host.shape[d]
                                      for d in (2, 3, 1, 0)) == stored):
                        host = np.transpose(host, (2, 3, 1, 0))
                    raw = jnp.asarray(host)
                    if self.mesh is not None:
                        raw = jax.device_put(raw,
                                             self.opt_state[k][i].sharding)
                    states.append(raw)
                self.opt_state[k] = tuple(states)
        self._refresh_compute_cache()
        return self
