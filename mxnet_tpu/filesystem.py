"""Pluggable filesystem layer + sharded InputSplit.

Parity: dmlc-core's ``dmlc::Stream``/``dmlc::InputSplit`` (SURVEY.md
§2.2) — the reference opens data URIs through a scheme-dispatched
filesystem (local, hdfs://, s3://) and shards input by byte ranges
aligned to record boundaries, so every worker reads only its slice of a
dataset that may live on a remote store.

Design here: a scheme registry mapping ``scheme://`` to a FileSystem
implementation.  Built in: local paths, ``mem://`` (in-process, the
dmlc-core unit-test pattern), and ``http(s)://`` byte-range reads — the
access pattern of every object store (S3/GCS/WebHDFS all serve Range
requests; point their presigned/REST URLs here).  Other schemes raise a
targeted error until an adapter is registered.

Byte-range splitting follows dmlc's recipe (input_split_base.cc): cut the
total byte span into ``num_parts`` even ranges, then align each boundary
forward to the next record head — RecordIO magic for .rec, newline for
text — so no record is read twice or skipped.
"""
from __future__ import annotations

import glob as _glob
import io
import os
import struct
import threading
from typing import Dict, List

from .base import MXNetError

_RECORDIO_MAGIC = 0xCED7230A


class FileSystem:
    """Interface (parity: dmlc::FileSystem)."""

    def open(self, path: str, mode: str = "rb"):
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list(self, pattern: str) -> List[str]:
        """Expand a glob-ish pattern to concrete paths."""
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    def open(self, path, mode="rb"):
        if "w" in mode or "a" in mode:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
        return open(path, mode)

    def size(self, path):
        return os.path.getsize(path)

    def exists(self, path):
        return os.path.exists(path)

    def list(self, pattern):
        hits = sorted(_glob.glob(pattern))
        return hits if hits else [pattern]


class MemFileSystem(FileSystem):
    """In-process filesystem (scheme ``mem://``) — the test double for
    remote stores, and a handy scratch space for notebooks."""

    def __init__(self):
        self._files: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def open(self, path, mode="rb"):
        if "a" in mode or "+" in mode:
            raise NotImplementedError(
                "mem:// supports only plain read ('rb') and truncating "
                "write ('wb') modes")
        if "w" in mode:
            fs = self

            class _Writer(io.BytesIO):
                def close(self_inner):
                    with fs._lock:
                        fs._files[path] = self_inner.getvalue()
                    super().close()

            return _Writer()
        with self._lock:
            if path not in self._files:
                raise FileNotFoundError(path)
            return io.BytesIO(self._files[path])

    def size(self, path):
        with self._lock:
            return len(self._files[path])

    def exists(self, path):
        with self._lock:
            return path in self._files

    def list(self, pattern):
        import fnmatch

        with self._lock:
            hits = sorted(p for p in self._files
                          if fnmatch.fnmatch(p, pattern))
        return hits if hits else [pattern]


class HttpFileSystem(FileSystem):
    """HTTP(S) byte-range filesystem — the working model of every remote
    object store the reference reaches through dmlc-core (S3, GCS, and
    WebHDFS all expose exactly this Range interface; presigned URLs work
    too, since size discovery falls back from HEAD to a 1-byte Range GET).
    Reads are lazy and buffered: `read` fetches block_size-aligned spans
    with a Range header, so small sequential reads (RecordIO headers)
    cost one round trip per block, and InputSplit shards pull just their
    slice of a remote file.  Servers that ignore Range (plain 200) are
    handled by downloading the body once and serving reads from cache."""

    def __init__(self, block_size: int = 1 << 20, timeout: float = 60.0):
        self.block_size = block_size
        self.timeout = timeout
        self._size_cache: Dict[str, int] = {}

    class _RangeFile(io.RawIOBase):
        def __init__(self, fs, url, size):
            self._fs = fs
            self._url = url
            self._size = size
            self._pos = 0
            self._buf = b""       # last fetched block
            self._buf_lo = 0
            self._whole = None    # full body cache (non-Range servers)

        def seekable(self):
            return True

        def readable(self):
            return True

        def seek(self, off, whence=io.SEEK_SET):
            if whence == io.SEEK_SET:
                self._pos = off
            elif whence == io.SEEK_CUR:
                self._pos += off
            else:
                self._pos = self._size + off
            return self._pos

        def tell(self):
            return self._pos

        def _fetch(self, lo, hi):
            """[lo, hi) from the server; populates _whole on 200."""
            data, partial = self._fs._fetch_range(self._url, lo, hi)
            if not partial:
                # server ignored the range: it sent the whole body — keep
                # it so later reads cost no further transfers
                self._whole = data
                return data[lo:hi]
            return data

        def read(self, n=-1):
            if n is None or n < 0:
                n = self._size - self._pos
            n = min(n, self._size - self._pos)
            if n <= 0:
                return b""
            if self._whole is not None:
                out = self._whole[self._pos:self._pos + n]
                self._pos += len(out)
                return out
            lo, hi = self._pos, self._pos + n
            blo, bhi = self._buf_lo, self._buf_lo + len(self._buf)
            if not (blo <= lo and hi <= bhi):
                # block-aligned read-ahead: one round trip covers many
                # small sequential reads (RecordIO header/payload/pad)
                bs = max(self._fs.block_size, n)
                fetch_lo = lo
                fetch_hi = min(lo + bs, self._size)
                self._buf = self._fetch(fetch_lo, fetch_hi)
                self._buf_lo = fetch_lo
                if self._whole is not None:
                    return self.read(n)
                blo = fetch_lo
            out = self._buf[lo - blo:lo - blo + n]
            self._pos += len(out)
            return out

    # auth hook: subclasses (S3/GS) rewrite the URI to a concrete endpoint
    # URL and inject auth headers; the base class is a pass-through
    def _prepare(self, uri, headers, method, data=None):
        return uri, headers

    # range hook: how [lo, hi) is expressed on the wire.  HTTP object
    # stores use a Range header; WebHDFS uses offset/length query params.
    # Returns (bytes, is_partial) — is_partial False means the whole body
    # arrived (server ignored the range).
    def _fetch_range(self, uri, lo, hi):
        with self._urlopen(uri, headers={
                "Range": f"bytes={lo}-{hi - 1}"}) as r:
            return r.read(), r.status == 206

    def _urlopen(self, uri, headers=None, method="GET", data=None):
        import urllib.request

        url, hdrs = self._prepare(uri, dict(headers or {}), method,
                                  data=data)
        req = urllib.request.Request(url, headers=hdrs, method=method,
                                     data=data)
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _put(self, path, data):
        raise MXNetError(f"{type(self).__name__} is read-only")

    def open(self, path, mode="rb"):
        if "a" in mode or "+" in mode:
            raise MXNetError("object stores support only 'rb' and "
                             "truncating 'wb'")
        if "w" in mode:
            if type(self)._put is HttpFileSystem._put:
                # fail at open, not buried in a close the caller (or GC)
                # might swallow
                raise MXNetError(f"{type(self).__name__} is read-only")
            fs = self

            class _Writer(io.BytesIO):
                """Buffer locally, upload the whole object on close —
                object stores write whole objects, not streams (the
                reference's dmlc-core S3 writer buffers the same way).

                A failed `with` body must NOT publish: a half-written
                buffer uploaded on close would overwrite a good remote
                object (WebHDFS create uses overwrite=true) with a
                truncated one, so __exit__ discards on exception."""

                _discard = False

                def __exit__(self_inner, exc_type, exc, tb):
                    if exc_type is not None:
                        self_inner._discard = True
                    return super().__exit__(exc_type, exc, tb)

                def close(self_inner):
                    if not self_inner.closed and not self_inner._discard:
                        fs._put(path, self_inner.getvalue())
                        fs._size_cache.pop(path, None)
                    super().close()

            return _Writer()
        return self._RangeFile(self, path, self.size(path))

    def size(self, path):
        import urllib.error

        cached = self._size_cache.get(path)
        if cached is not None:
            return cached

        def done(n):
            self._size_cache[path] = n
            return n

        try:
            with self._urlopen(path, method="HEAD") as r:
                cl = r.headers["Content-Length"]
                if cl is not None:
                    return done(int(cl))
        except (urllib.error.URLError, OSError):
            pass  # presigned URLs often sign GET only — fall through
        try:
            # 1-byte Range GET: Content-Range carries the total size
            with self._urlopen(path, headers={"Range": "bytes=0-0"}) as r:
                cr = r.headers.get("Content-Range")  # "bytes 0-0/12345"
                total = cr.rsplit("/", 1)[1] if cr and "/" in cr else None
                if total and total != "*":  # '*' = RFC 7233 unknown length
                    return done(int(total))
                cl = r.headers.get("Content-Length")
                if r.status == 200 and cl is not None:
                    return done(int(cl))  # server sent the whole body
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise MXNetError(f"http filesystem: cannot reach {path!r}: "
                             f"{exc}") from exc
        raise MXNetError(f"http filesystem: server for {path!r} reports "
                         "no usable Content-Length/Content-Range; cannot "
                         "do ranged reads over a chunked stream")

    def exists(self, path):
        try:
            self.size(path)
            return True
        except MXNetError:
            return False

    def list(self, pattern):
        return [pattern]  # no server-side listing over plain HTTP


_EMPTY_SHA256 = (
    "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855")


def _sigv4_headers(method, host, path, headers, access_key, secret_key,
                   region, amzdate, session_token=None, service="s3",
                   payload_hash=_EMPTY_SHA256):
    """AWS Signature Version 4 (GET/HEAD, and PUT when ``payload_hash``
    is the body's sha256).

    Pure-stdlib signing of the canonical request -> string-to-sign ->
    derived key chain, per the SigV4 spec; returns the full header dict
    including Authorization.  Split out from S3FileSystem so it can be
    pinned against the published AWS test vector (test_filesystem.py).
    """
    import hashlib
    import hmac
    from urllib.parse import quote

    hdrs = dict(headers)
    hdrs["x-amz-date"] = amzdate
    hdrs["x-amz-content-sha256"] = payload_hash
    if session_token:
        hdrs["x-amz-security-token"] = session_token
    hdrs["host"] = host

    canon_uri = quote(path, safe="/~")
    items = sorted((k.lower(), " ".join(str(v).split()))
                   for k, v in hdrs.items())
    signed = ";".join(k for k, _ in items)
    canon_headers = "".join(f"{k}:{v}\n" for k, v in items)
    canonical = "\n".join([method, canon_uri, "", canon_headers, signed,
                           payload_hash])
    datestamp = amzdate[:8]
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amzdate, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(_hmac(_hmac(k, region), service), "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    hdrs["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    del hdrs["host"]  # urllib sets Host itself; it was only for signing
    return hdrs


class S3FileSystem(HttpFileSystem):
    """s3://bucket/key with AWS SigV4 request signing (parity: dmlc-core's
    USE_S3 InputSplit backend, make/config.mk:138-146; credentials come
    from the same env vars the reference documents in
    docs/how_to/env_var.md — AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY).

    Also honored: AWS_SESSION_TOKEN, AWS_REGION/AWS_DEFAULT_REGION
    (default us-east-1), and S3_ENDPOINT (custom/on-prem endpoint,
    path-style addressing — also how the tests point the signer at a
    local double).  Unsigned public-bucket access works when no
    credentials are set.  Read-only, like the reference's S3 reader;
    listing requires a full URI (no server-side wildcard).
    """

    def __init__(self, **kw):
        super().__init__(**kw)

    def _creds(self):
        env = os.environ
        return (env.get("AWS_ACCESS_KEY_ID"),
                env.get("AWS_SECRET_ACCESS_KEY"),
                env.get("AWS_SESSION_TOKEN"),
                env.get("AWS_REGION",
                        env.get("AWS_DEFAULT_REGION", "us-east-1")))

    def _prepare(self, uri, headers, method, data=None):
        from urllib.parse import quote, urlsplit

        parts = urlsplit(uri)
        bucket, key = parts.netloc, parts.path.lstrip("/")
        endpoint = os.environ.get("S3_ENDPOINT")
        if endpoint:
            endpoint = endpoint.rstrip("/")
            base = endpoint if "://" in endpoint else "https://" + endpoint
            ep = urlsplit(base)
            host = ep.netloc
            # any endpoint path prefix (S3 behind a reverse-proxy subpath)
            # must be part of the SIGNED canonical URI too, or the server
            # rejects with SignatureDoesNotMatch
            path = f"{ep.path}/{bucket}/{key}"
            url = f"{ep.scheme}://{ep.netloc}" + quote(path, safe="/~")
        else:
            _, _, _, region = self._creds()
            host = f"{bucket}.s3.{region}.amazonaws.com"
            path = "/" + key
            url = f"https://{host}" + quote(path, safe="/~")
        ak, sk, tok, region = self._creds()
        if ak and sk:
            import datetime as _dt
            import hashlib

            amzdate = _dt.datetime.now(_dt.timezone.utc).strftime(
                "%Y%m%dT%H%M%SZ")
            payload_hash = (hashlib.sha256(data).hexdigest()
                            if data is not None else _EMPTY_SHA256)
            headers = _sigv4_headers(method, host, path, headers, ak, sk,
                                     region, amzdate, tok,
                                     payload_hash=payload_hash)
        return url, headers

    def _put(self, path, data):
        """Signed PUT of a whole object (parity: dmlc-core's S3 write
        stream, which buffers and multipart-uploads; whole-object PUT
        covers the checkpoint/save_checkpoint use case)."""
        with self._urlopen(path, method="PUT", data=data) as r:
            if r.status not in (200, 201):
                raise MXNetError(f"s3 PUT {path!r} -> HTTP {r.status}")


class GSFileSystem(HttpFileSystem):
    """gs://bucket/object over the GCS XML/JSON endpoint with a bearer
    token (GS_OAUTH2_TOKEN or GOOGLE_OAUTH_ACCESS_TOKEN env; unset =
    unauthenticated access to public objects).  GS_ENDPOINT overrides the
    endpoint for test doubles / emulators."""

    def _prepare(self, uri, headers, method, data=None):
        from urllib.parse import quote, urlsplit

        parts = urlsplit(uri)
        bucket, key = parts.netloc, parts.path.lstrip("/")
        base = os.environ.get("GS_ENDPOINT",
                              "https://storage.googleapis.com").rstrip("/")
        url = base + quote(f"/{bucket}/{key}", safe="/~")
        token = os.environ.get("GS_OAUTH2_TOKEN",
                               os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN"))
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return url, headers

    def _put(self, path, data):
        # the GCS XML API accepts whole-object PUT on the same URL shape
        with self._urlopen(path, method="PUT", data=data) as r:
            if r.status not in (200, 201):
                raise MXNetError(f"gs PUT {path!r} -> HTTP {r.status}")


class WebHdfsFileSystem(HttpFileSystem):
    """hdfs://namenode[:port]/path over the WebHDFS REST API (the
    transport dmlc-core's libhdfs-free deployments use; parity for the
    reference's USE_HDFS InputSplit backend without a JVM).

    Ranged reads map to ``op=OPEN&offset=&length=`` (the namenode's 307
    redirect to a datanode is followed by urllib); size comes from
    ``op=GETFILESTATUS``.  Auth: ``HADOOP_USER_NAME`` adds the simple
    ``user.name`` query credential; ``WEBHDFS_TOKEN`` adds a delegation
    token.  ``WEBHDFS_ENDPOINT`` overrides the namenode address (also
    how tests point at a loopback double); default port 9870.
    """

    def _base(self, parts):
        ep = os.environ.get("WEBHDFS_ENDPOINT")
        if ep:
            ep = ep.rstrip("/")
            return ep if "://" in ep else "http://" + ep
        host = parts.netloc or "localhost"
        if ":" not in host:
            host += ":9870"
        return f"http://{host}"

    def _url(self, uri, op, extra=""):
        from urllib.parse import quote, urlsplit

        parts = urlsplit(uri)
        auth = ""
        user = os.environ.get("HADOOP_USER_NAME")
        if user:
            auth += "&user.name=" + quote(user, safe="")
        token = os.environ.get("WEBHDFS_TOKEN")
        if token:
            auth += "&delegation=" + quote(token, safe="")
        return (f"{self._base(parts)}/webhdfs/v1"
                f"{quote(parts.path, safe='/~')}?op={op}{extra}{auth}")

    def _fetch_range(self, uri, lo, hi):
        url = self._url(uri, "OPEN", f"&offset={lo}&length={hi - lo}")
        with self._urlopen(url) as r:
            return r.read(), True  # OPEN always returns exactly the span

    def _put(self, path, data):
        """WebHDFS CREATE (the two-step namenode->datanode dance): PUT
        op=CREATE gets a 307 with the datanode Location, the body goes
        there.  Servers that skip the redirect (single-node doubles)
        accept the body on the first request."""
        import urllib.error
        import urllib.request

        url = self._url(path, "CREATE", "&overwrite=true")

        class _NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **kw):
                return None

        opener = urllib.request.build_opener(_NoRedirect)
        req = urllib.request.Request(url, method="PUT", data=b"")
        try:
            with opener.open(req, timeout=self.timeout) as r:
                status, location = r.status, r.headers.get("Location")
        except urllib.error.HTTPError as e:
            status, location = e.code, e.headers.get("Location")
        if status == 307:
            if not location:
                raise MXNetError(
                    f"webhdfs CREATE {path!r}: 307 without Location")
            target = location
        elif status in (200, 201):
            # no redirect (single-node doubles): the body goes straight
            # to the namenode URL
            target = url
        else:
            raise MXNetError(f"webhdfs CREATE {path!r} -> HTTP {status}")
        req2 = urllib.request.Request(target, method="PUT", data=data)
        with urllib.request.urlopen(req2, timeout=self.timeout) as r2:
            if r2.status not in (200, 201):
                raise MXNetError(
                    f"webhdfs PUT {path!r} -> HTTP {r2.status}")
        self._size_cache.pop(path, None)

    def size(self, path):
        import json as _json

        cached = self._size_cache.get(path)
        if cached is not None:
            return cached
        try:
            with self._urlopen(self._url(path, "GETFILESTATUS")) as r:
                st = _json.loads(r.read().decode())
            n = int(st["FileStatus"]["length"])
        except Exception as exc:  # noqa: BLE001
            raise MXNetError(
                f"webhdfs: cannot stat {path!r}: {exc}") from exc
        self._size_cache[path] = n
        return n

    def _liststatus(self, diruri):
        import json as _json

        with self._urlopen(self._url(diruri, "LISTSTATUS")) as r:
            st = _json.loads(r.read().decode())
        return st["FileStatuses"]["FileStatus"]

    def list(self, pattern):
        import fnmatch
        import urllib.error
        from urllib.parse import urlsplit

        parts = urlsplit(pattern)
        if any(c in parts.path for c in "*?["):
            # glob: LISTSTATUS the parent dir and fnmatch basenames (dmlc
            # wildcard semantics; one level, like dmlc's InputSplit)
            parent, _, leaf = parts.path.rstrip("/").rpartition("/")
            base = f"{parts.scheme}://{parts.netloc}{parent}"
            try:
                entries = self._liststatus(base)
            except (urllib.error.URLError, OSError, KeyError,
                    ValueError) as exc:
                raise MXNetError(
                    f"webhdfs: cannot list {base!r} for pattern "
                    f"{pattern!r}: {exc}") from exc
            hits = sorted(f"{base}/{e['pathSuffix']}" for e in entries
                          if fnmatch.fnmatch(e["pathSuffix"], leaf))
            return hits if hits else [pattern]
        try:
            entries = self._liststatus(pattern)
        except Exception:
            return [pattern]  # plain file (or unlistable): single entry
        base = pattern.rstrip("/")
        return [base if e["pathSuffix"] == "" else
                f"{base}/{e['pathSuffix']}" for e in entries]


_REGISTRY: Dict[str, FileSystem] = {
    "": LocalFileSystem(),
    "file": LocalFileSystem(),
    "mem": MemFileSystem(),
    "http": HttpFileSystem(),
    "https": HttpFileSystem(),
    "s3": S3FileSystem(),
    "gs": GSFileSystem(),
    "hdfs": WebHdfsFileSystem(),
    "webhdfs": WebHdfsFileSystem(),
}


def register_filesystem(scheme: str, fs: FileSystem):
    """Plug in a remote store adapter (s3/hdfs/gs/...)."""
    _REGISTRY[scheme.rstrip(":/")] = fs


def _split_scheme(uri: str):
    if "://" in uri:
        scheme, rest = uri.split("://", 1)
        return scheme, uri
    return "", uri


def get_filesystem(uri: str) -> FileSystem:
    scheme, _ = _split_scheme(uri)
    fs = _REGISTRY.get(scheme)
    if fs is None:
        raise MXNetError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(register one with mxnet_tpu.filesystem.register_filesystem; "
            f"built-ins: {sorted(_REGISTRY)})")
    return fs


def _strip_local(uri: str) -> str:
    return uri[7:] if uri.startswith("file://") else uri


def open_uri(uri: str, mode: str = "rb"):
    scheme, _ = _split_scheme(uri)
    path = _strip_local(uri) if scheme in ("", "file") else uri
    return get_filesystem(uri).open(path, mode)


def is_remote(uri: str) -> bool:
    """True when the URI names a non-local filesystem (the save/load
    paths stage through a temp file + open_uri for these — checkpoints
    write straight to s3://, gs://, hdfs://, mem://)."""
    scheme, _ = _split_scheme(str(uri))
    return scheme not in ("", "file")


class InputSplit:
    """Byte-range sharded reader over one or more URIs (parity:
    dmlc::InputSplit::Create with part_index/num_parts).

    ``uri`` may be a single path, a comma-separated list, or a glob.
    ``split_type``: 'recordio' aligns shard starts to the RecordIO magic;
    'text' aligns to the next newline.  Iterating yields whole records
    (payload bytes for recordio, lines without trailing newline for text).
    """

    def __init__(self, uri: str, part_index: int = 0, num_parts: int = 1,
                 split_type: str = "recordio"):
        if not 0 <= part_index < num_parts:
            raise MXNetError(f"part_index {part_index} out of range "
                             f"({num_parts} parts)")
        self.split_type = split_type
        pieces = []
        for u in uri.split(","):
            u = u.strip()
            if not u:
                continue
            fs = get_filesystem(u)
            scheme, _ = _split_scheme(u)
            raw = _strip_local(u) if scheme in ("", "file") else u
            for path in fs.list(raw):
                pieces.append((fs, path, fs.size(path)))
        if not pieces:
            raise MXNetError(f"InputSplit: nothing matches {uri!r}")
        self._pieces = pieces
        total = sum(sz for _, _, sz in pieces)
        lo = total * part_index // num_parts
        hi = total * (part_index + 1) // num_parts
        self._lo, self._hi = lo, hi

    # ------------------------------------------------------------- iteration
    def __iter__(self):
        # walk files, tracking the global byte offset; align the start of
        # our [lo, hi) range to the next record head, and keep reading the
        # record that STARTS before hi even if it ends after (dmlc rule:
        # a record belongs to the shard its head falls in).  Only the
        # shard's own byte range is read (seek-based), never whole files.
        global_off = 0
        for fs, path, sz in self._pieces:
            file_lo = max(self._lo - global_off, 0)
            file_hi = min(self._hi - global_off, sz)
            if file_hi <= 0 or file_lo >= sz:
                global_off += sz
                continue
            with fs.open(path, "rb") as f:
                if self.split_type == "recordio":
                    yield from self._iter_recordio(f, file_lo, file_hi, sz)
                else:
                    yield from self._iter_text(f, file_lo, file_hi, sz)
            global_off += sz

    def _iter_recordio(self, f, lo, hi, sz):
        start = (lo + 3) // 4 * 4  # records live at 4-aligned offsets only
        f.seek(start)
        data = f.read(hi - start)  # the shard's slice; extended on demand
        pos = self._align_recordio(data, 0)
        end_rel = hi - start
        while pos < end_rel:
            if pos + 8 > len(data):
                # header cut by the slice boundary — it starts before hi,
                # so the record is ours; pull in the rest of the header
                extra = f.read(pos + 8 - len(data))
                data += extra
                if pos + 8 > len(data):
                    return
            magic, lrec = struct.unpack_from("<II", data, pos)
            if magic != _RECORDIO_MAGIC:
                pos = self._align_recordio(data, pos + 4)
                continue
            length = lrec & ((1 << 29) - 1)
            need = pos + 8 + ((length + 3) // 4) * 4
            if need > len(data):
                # the record straddling hi belongs to this shard: pull in
                # exactly its remainder
                extra = f.read(need - len(data))
                data += extra
                if need > len(data):
                    return  # truncated tail — not a complete record
            yield data[pos + 8: pos + 8 + length]
            pos = need

    @staticmethod
    def _align_recordio(data, pos):
        """First position >= pos that starts a PLAUSIBLE record: the magic
        at a 4-aligned offset whose length word chains to EOF-or-another-
        magic.  The chain check rejects payload bytes that merely look
        like the magic (a payload is stored raw here; scanning alone
        cannot distinguish it)."""
        n = len(data)
        magic = struct.pack("<I", _RECORDIO_MAGIC)
        pos = (pos + 3) // 4 * 4
        while pos + 4 <= n:
            if data[pos:pos + 4] == magic:
                if pos + 8 > n:
                    return pos  # header cut by the slice: caller extends
                (lrec,) = struct.unpack_from("<I", data, pos + 4)
                nxt = pos + 8 + (((lrec & ((1 << 29) - 1)) + 3) // 4) * 4
                if nxt >= n or data[nxt:nxt + 4] == magic:
                    return pos
            pos += 4
        return n

    def _iter_text(self, f, lo, hi, sz):
        if lo == 0:
            start = 0
        else:
            # a shard starts at the first line head AFTER byte lo-1
            f.seek(lo - 1)
            chunk = f.read(hi - lo + 1)
            nl = chunk.find(b"\n")
            if nl == -1:
                return
            start = lo - 1 + nl + 1
        f.seek(start)
        data = f.read(hi - start)
        pos = 0
        end_rel = hi - start
        while pos < end_rel and pos < len(data):
            end = data.find(b"\n", pos)
            while end == -1:
                extra = f.read(1 << 16)  # line straddles hi: extend
                if not extra:
                    end = len(data)
                    break
                data += extra
                end = data.find(b"\n", pos)
            yield data[pos:end]
            pos = end + 1
