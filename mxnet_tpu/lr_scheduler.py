"""Learning-rate schedulers (parity: python/mxnet/lr_scheduler.py)."""
from __future__ import annotations

import logging
import math


class LRScheduler:
    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every `step` updates (parity: FactorScheduler)."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("lr hit stop_factor_lr %.2e", self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at given update milestones (parity: MultiFactorScheduler)."""

    def __init__(self, step, factor=1.0):
        super().__init__()
        if not all(step[i] < step[i + 1] for i in range(len(step) - 1)):
            raise ValueError("steps must be increasing")
        self.step = list(step)
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update):
        while self.cur_step_ind <= len(self.step) - 1:
            if num_update > self.step[self.cur_step_ind]:
                self.count = self.step[self.cur_step_ind]
                self.cur_step_ind += 1
                self.base_lr *= self.factor
            else:
                return self.base_lr
        return self.base_lr


class WarmupCosineScheduler(LRScheduler):
    """Linear warmup to base_lr, then cosine decay to ``final_lr`` over
    ``total_steps`` (beyond-reference: the transformer-era schedule;
    the v0.9.4 reference ships only Factor/MultiFactor).  Stateless in
    num_update, so checkpoint resume lands on the exact same curve."""

    def __init__(self, total_steps, warmup_steps=0, final_lr=0.0):
        super().__init__()
        if total_steps < 1:
            raise ValueError("total_steps must be >= 1")
        if not 0 <= warmup_steps < total_steps:
            raise ValueError("need 0 <= warmup_steps < total_steps")
        self.total_steps = int(total_steps)
        self.warmup_steps = int(warmup_steps)
        self.final_lr = float(final_lr)

    def __call__(self, num_update):
        if self.warmup_steps and num_update <= self.warmup_steps:
            return self.base_lr * num_update / self.warmup_steps
        t = min(num_update, self.total_steps) - self.warmup_steps
        span = self.total_steps - self.warmup_steps
        cos = 0.5 * (1.0 + math.cos(math.pi * t / span))
        return self.final_lr + (self.base_lr - self.final_lr) * cos
