"""Row-sparse gradient subsystem.

Parity: python/mxnet/ndarray/sparse.py (``RowSparseNDArray``) + the
row_sparse storage type of src/ndarray/ndarray.cc — the storage format
the source paper's KVStore exists to serve: huge embedding tables whose
per-batch gradient touches only the rows the batch looked up
(SURVEY.md; ROADMAP item 5).

TPU-native shape discipline: the reference materializes a
variable-length ``(indices, values)`` pair per backward (unique row
count changes every batch), which would retrace a jitted program per
batch.  Here everything is **shape-stable**: a row-sparse gradient
carries exactly one slot per looked-up id (``K = prod(idx.shape)``,
static), coalesced in-trace by sort + segment-sum — duplicate ids keep
their slot with a zero row, the first occurrence holds the sum.  Dense
conversion is therefore defined as *scatter-add* (equal to the
reference's row-set when indices are unique).

Three consumers share ONE row-update program builder so their math is
bit-identical:

- the executor's Embedding backward (``__grad_stype__="row_sparse"``
  variables) emits the coalesced ``(indices, values)`` pair in-trace,
- ``kvstore_fused``'s sparse buckets run :func:`make_row_program` —
  gather touched rows, apply the shared optim_rules kernel, scatter-add
  the masked delta (lazy-state semantics: untouched rows' weight AND
  optimizer state are left byte-identical),
- the eager per-key fallback (:func:`eager_update`) runs the SAME
  jitted program at nparts=1, so fused-vs-eager interleave stays
  consistent.

``MXTPU_SPARSE_UPDATE=0`` disables the row-sparse grad emission at bind
(grads come back dense) and thereby the whole sparse path,
bit-identically restoring the dense behavior.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import ndarray as nd
from . import telemetry as _tm
from .base import MXNetError
from .ndarray import NDArray

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_SPARSE_ROWS = _tm.counter(
    "kvstore_sparse_rows_total",
    "gradient row slots pushed through the sparse update path (one per "
    "looked-up id, duplicates included — host-known, never a device "
    "sync)", labels=("store",))
_TM_SPARSE_DENSITY = _tm.histogram(
    "kvstore_sparse_density",
    "pushed row slots / table rows per sparse push (the touched "
    "fraction upper bound; <1 means the dense scatter was wasteful)",
    labels=("store",),
    buckets=(1e-5, 1e-4, 1e-3, 1e-2, 0.05, 0.1, 0.25, 0.5, 1.0))
_TM_SPARSE_SEC = _tm.histogram(
    "kvstore_sparse_update_seconds",
    "wall time of one batched sparse-bucket update (touched-rows-only "
    "jitted programs; dispatch, not device completion)",
    labels=("store",))


def sparse_update_enabled() -> bool:
    """MXTPU_SPARSE_UPDATE gate (default on).

    ``0`` makes ``simple_bind`` allocate dense gradient buffers for
    ``grad_stype="row_sparse"`` variables, so Embedding backward falls
    back to the dense scatter and every downstream consumer (kvstore,
    optimizer) sees the pre-sparse behavior bit-identically.  Sampled
    at bind time."""
    from .base import parse_bool

    return parse_bool(os.environ.get("MXTPU_SPARSE_UPDATE", "1"))


# ---------------------------------------------------------------------------
# RowSparseNDArray
# ---------------------------------------------------------------------------
class RowSparseNDArray(NDArray):
    """A ``(indices, values)`` pair standing for a tensor whose rows
    outside ``indices`` are zero (parity: mx.nd.sparse.RowSparseNDArray).

    ``indices`` is int32 ``(K,)`` sorted ascending; ``values`` is
    ``(K,) + shape[1:]``.  Duplicate indices are allowed (the in-trace
    coalesce keeps one slot per looked-up id) and SUM on dense
    conversion, so ``todense()`` is exact for both unique-row user
    arrays and coalesced gradient emissions (duplicate slots carry zero
    rows).  Dense reads (``_read``) raise — silent densification of a
    table-sized sparse array is the bug this type exists to prevent;
    use ``.todense()`` / ``.data`` / ``.indices`` explicitly."""

    __slots__ = ("_indices", "_values", "_full_shape")

    stype = "row_sparse"

    def __init__(self, indices, values, shape):
        ind = indices if isinstance(indices, NDArray) else NDArray(
            jnp.asarray(np.asarray(indices), dtype=jnp.int32))
        val = values if isinstance(values, NDArray) else NDArray(
            jnp.asarray(values))
        shape = tuple(int(s) for s in shape)
        if len(ind.shape) != 1:
            raise MXNetError(
                f"row_sparse indices must be 1-D, got {ind.shape}")
        if tuple(val.shape) != (ind.shape[0],) + shape[1:]:
            raise MXNetError(
                f"row_sparse values shape {val.shape} does not match "
                f"{(ind.shape[0],) + shape[1:]} (indices {ind.shape}, "
                f"shape {shape})")
        self._indices = ind
        self._values = val
        self._full_shape = shape
        # NDArray plumbing: the chunk aliases the values storage so
        # generic context/dtype/engine accounting keep working
        self._chunk = val._chunk
        self._index = None
        self._shape = None

    # -------------------------------------------------------------- structure
    @property
    def indices(self) -> NDArray:
        return self._indices

    @property
    def data(self) -> NDArray:
        """The value rows (parity: RowSparseNDArray.data)."""
        return self._values

    values = data

    @property
    def shape(self):
        return self._full_shape

    @property
    def size(self):
        return int(np.prod(self._full_shape)) if self._full_shape else 1

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def context(self):
        return self._values.context

    ctx = context

    def __len__(self):
        return self._full_shape[0]

    def __repr__(self):
        return (f"<RowSparseNDArray {'x'.join(map(str, self.shape))} "
                f"rows={self._indices.shape[0]} @{self.context}>")

    # ------------------------------------------------------------------ reads
    def _read(self):
        raise MXNetError(
            "row_sparse NDArray cannot be read as a dense array; use "
            ".todense() / .tostype('default') (explicit) or .indices/"
            ".data for the sparse parts")

    def asnumpy(self):
        return self.todense().asnumpy()

    def wait_to_read(self):
        self._indices.wait_to_read()
        self._values.wait_to_read()

    def todense(self) -> NDArray:
        """Materialize the dense tensor (scatter-add of the value rows)."""
        idx = self._indices._read()
        vals = self._values._read()
        dense = jnp.zeros(self._full_shape, dtype=vals.dtype)
        return NDArray(dense.at[idx].add(vals))

    def tostype(self, stype: str):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return self.todense()
        raise MXNetError(f"unknown storage type {stype!r}")

    def copy(self) -> "RowSparseNDArray":
        return RowSparseNDArray(self._indices.copy(), self._values.copy(),
                                self._full_shape)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            if other._full_shape != self._full_shape:
                raise MXNetError(
                    f"copyto: shape mismatch {self._full_shape} vs "
                    f"{other._full_shape}")
            other._set_rows(self._indices._read(), self._values._read())
            return other
        if isinstance(other, NDArray):
            other._set(self.todense()._read())
            return other
        return super().copyto(other)

    # ----------------------------------------------------------------- writes
    def _set_rows(self, indices_raw, values_raw):
        """Rebind the (indices, values) pair in place — the executor's
        backward write and kvstore row pulls land here.  Shapes may
        change between steps (a rebind with a new batch size); only the
        row width and full shape are pinned."""
        if not isinstance(indices_raw, jax.Array):
            indices_raw = jnp.asarray(np.asarray(indices_raw),
                                      dtype=jnp.int32)
        if not isinstance(values_raw, jax.Array):
            values_raw = jnp.asarray(values_raw)
        if tuple(values_raw.shape[1:]) != self._full_shape[1:] or \
                values_raw.shape[0] != indices_raw.shape[0]:
            raise MXNetError(
                f"row_sparse write: values {values_raw.shape} does not "
                f"match indices {indices_raw.shape} + row shape "
                f"{self._full_shape[1:]}")
        self._indices._chunk.write(indices_raw)
        self._values._chunk.write(values_raw)
        self._chunk = self._values._chunk
        return self

    def _set(self, new_data, _from_engine=False):
        raise MXNetError(
            "row_sparse NDArray does not support dense writes; use "
            "_set_rows(indices, values)")


def _as_stype(arr) -> str:
    return getattr(arr, "stype", "default")


# ---------------------------------------------------------------------------
# constructors (parity: mx.nd.sparse.row_sparse_array / mx.nd.sparse.zeros)
# ---------------------------------------------------------------------------
def row_sparse_array(arg, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    """Parity: mx.nd.sparse.row_sparse_array.

    ``arg`` is either ``(data, indices)`` (rows + their row ids) or a
    dense array-like to compress (non-zero rows kept)."""
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        data = np.asarray(data.asnumpy() if isinstance(data, NDArray)
                          else data, dtype=dtype)
        indices = np.asarray(
            indices.asnumpy() if isinstance(indices, NDArray) else indices,
            dtype=np.int32)
        order = np.argsort(indices, kind="stable")
        indices, data = indices[order], data[order]
        if shape is None:
            top = int(indices[-1]) + 1 if indices.size else 0
            shape = (top,) + data.shape[1:]
        if indices.size and (int(indices[0]) < 0
                             or int(indices[-1]) >= shape[0]):
            raise MXNetError(
                f"row_sparse_array: row id out of bounds for shape "
                f"{tuple(shape)}")
        return RowSparseNDArray(
            NDArray(jnp.asarray(indices), ctx=ctx),
            NDArray(jnp.asarray(data), ctx=ctx), tuple(shape))
    if isinstance(arg, RowSparseNDArray):
        return arg.copy()
    dense = np.asarray(arg.asnumpy() if isinstance(arg, NDArray) else arg,
                       dtype=dtype)
    if shape is None:
        shape = dense.shape
    nz = np.flatnonzero(dense.reshape(dense.shape[0], -1).any(axis=1))
    return RowSparseNDArray(
        NDArray(jnp.asarray(nz.astype(np.int32)), ctx=ctx),
        NDArray(jnp.asarray(dense[nz]), ctx=ctx), tuple(shape))


def zeros(stype, shape, ctx=None, dtype=np.float32):
    """Parity: mx.nd.sparse.zeros — an all-zero array of the given
    storage type (a row_sparse zero holds no rows)."""
    if stype == "default":
        return nd.zeros(shape, ctx=ctx, dtype=dtype)
    if stype != "row_sparse":
        raise MXNetError(f"unknown storage type {stype!r}")
    shape = tuple(shape)
    return RowSparseNDArray(
        NDArray(jnp.zeros((0,), dtype=jnp.int32), ctx=ctx),
        NDArray(jnp.zeros((0,) + shape[1:], dtype=jnp.dtype(dtype)),
                ctx=ctx), shape)


def full_row_sparse(arr: NDArray) -> RowSparseNDArray:
    """A row_sparse view-copy holding EVERY row (indices = arange) —
    how a dense embedding table enters ``KVStore.init`` for a key that
    will receive row-sparse pushes."""
    raw = arr._read()
    return RowSparseNDArray(
        NDArray(jnp.arange(raw.shape[0], dtype=jnp.int32)),
        NDArray(raw), tuple(raw.shape))


# ---------------------------------------------------------------------------
# graph analysis: which variables are row-sparse-gradient eligible
# ---------------------------------------------------------------------------
def annotated_rs_names(symbol) -> List[str]:
    """Variable names carrying ``__grad_stype__="row_sparse"``."""
    return [n.name for n in symbol.nodes
            if n.is_variable
            and n.extra_attrs.get("__grad_stype__") == "row_sparse"]


def rs_plan(symbol) -> Dict[str, object]:
    """{weight name: its Embedding node} for every annotated variable
    whose ONLY consumer is one Embedding op reading it as the weight —
    the structural condition under which the executor may emit the
    row-sparse gradient instead of the dense scatter.  A weight with
    any other consumer (tied decoder, regularizer term) falls back to
    dense silently: the dense grad is always correct."""
    rs_names = set(annotated_rs_names(symbol))
    if not rs_names:
        return {}
    consumers: Dict[str, List] = {w: [] for w in rs_names}
    for node in symbol.nodes:
        if node.is_variable:
            continue
        for pos, (src, _oidx) in enumerate(node.inputs):
            if src.is_variable and src.name in rs_names:
                consumers[src.name].append((node, pos))
    plan = {}
    for wname, cons in consumers.items():
        if len(cons) == 1 and cons[0][0].op == "Embedding" \
                and cons[0][1] == 1:
            plan[wname] = cons[0][0]
    return plan


# ---------------------------------------------------------------------------
# in-trace row math (shared by executor backward + kvstore programs)
# ---------------------------------------------------------------------------
def coalesce_rows(idx, vals):
    """Sort ids and sum duplicate rows into the first occurrence —
    shape-stable (K slots in, K slots out; later duplicates keep their
    id with a zero row).  Returns ``(sorted_ids, summed_vals,
    first_mask)``."""
    order = jnp.argsort(idx)
    sid = idx[order]
    sval = vals[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(first) - 1
    summed = jax.ops.segment_sum(sval, seg, num_segments=idx.shape[0])
    mask = first.reshape((-1,) + (1,) * (vals.ndim - 1))
    return sid, jnp.where(mask, summed[seg], 0), first


def make_row_program(rule_name: str, opt_params: tuple, wd_mult: float,
                     nparts: int, sentinel: bool = False,
                     out_sharding=None, donate: bool = True,
                     mp: bool = False, scaling: bool = False):
    """Build the ONE jitted touched-rows-only update program for a
    sparse bucket: concat the per-device ``(idx, vals)`` parts,
    coalesce by sort + segment-sum, gather the touched weight/state
    rows, run the shared optim_rules kernel on them, and scatter-add
    the masked delta back — untouched rows (and duplicate slots) are
    exact no-ops, which IS the lazy-update semantics.  ``lr`` is a
    traced scalar; everything else is static and keys the program in
    the executor LRU.  With ``out_sharding`` (a mesh-sharded table) the
    fresh table and state are constrained back to the table's
    sharding, so GSPMD keeps the shards in place and routes rows
    per-shard.  The eager fallback runs this same builder at
    ``nparts=1`` — fused vs eager is the same compiled math.

    The table and state ARE donated (``donate``): XLA aliases the
    outputs onto the inputs, so a step costs O(touched rows), not a
    full-table copy — the whole point of the sparse path.  Donation is
    safe because every caller immediately rebinds the owning chunks to
    the outputs; the one observable consequence is that an NDArray
    which adopted the table buffer via a zero-copy pull raises
    "deleted/donated" if read after the NEXT push but before its pull
    (push/pull are adjacent in every Module step) — see docs/sparse.md.

    ``mp`` (AMP fp32 master rows, docs/amp.md): the LAST state slot is
    the fp32 master TABLE of a low-precision table — touched master
    rows gather, the rule runs in fp32 on them, and BOTH the master
    rows and the freshly-cast table rows scatter back in this same
    program; untouched rows of table and master stay byte-identical
    (the lazy contract).  ``scaling`` (AMP dynamic loss scaling): a
    traced scale unscales the pushed rows in-trace, a finite flag
    selects old-vs-new rows (the skip-step lattice), and the flag
    rides out for the scale-update program.
    """
    from . import executor as _executor
    from .optim_rules import sparse_rule

    nslots, update = sparse_rule(rule_name, dict(opt_params))
    del nslots

    def step(idx_parts, val_parts, w, slots, lr, scale=None):
        idx = idx_parts[0] if len(idx_parts) == 1 \
            else jnp.concatenate(idx_parts)
        vals = val_parts[0] if len(val_parts) == 1 \
            else jnp.concatenate(val_parts)
        fin = jnp.isfinite(vals).all() if scaling else None
        if scaling:
            vals = vals * (1.0 / scale).astype(vals.dtype)
        sid, gvals, first = coalesce_rows(idx, vals)
        if mp:
            master, rslots = slots[-1], slots[:-1]
            w_rows = jnp.take(master, sid, axis=0)
            gvals = gvals.astype(jnp.float32)
        else:
            master, rslots = None, slots
            w_rows = jnp.take(w, sid, axis=0)
        s_rows = tuple(jnp.take(s, sid, axis=0) for s in rslots)
        new_rows, new_s_rows = update(w_rows, gvals, s_rows, lr, wd_mult)
        if scaling:
            new_rows = jnp.where(fin, new_rows, w_rows)
            new_s_rows = tuple(jnp.where(fin, ns, sr)
                               for ns, sr in zip(new_s_rows, s_rows))
        mask = first.reshape((-1,) + (1,) * (vals.ndim - 1))
        delta = jnp.where(mask, new_rows - w_rows, 0)
        new_slots = tuple(
            s.at[sid].add(jnp.where(mask, (ns - sr).astype(s.dtype), 0))
            for s, ns, sr in zip(rslots, new_s_rows, s_rows))
        if mp:
            new_master = master.at[sid].add(delta)
            # table rows become cast-of-master: add (cast(new_row) -
            # current_row) on first occurrences — a masked SET, so the
            # bf16 row is always the exact cast of its fp32 master
            cur_rows = jnp.take(w, sid, axis=0)
            new_w = w.at[sid].add(
                jnp.where(mask, new_rows.astype(w.dtype) - cur_rows, 0))
            new_slots = new_slots + (new_master,)
        else:
            new_w = w.at[sid].add(delta.astype(w.dtype))
        if out_sharding is not None:
            csc = jax.lax.with_sharding_constraint
            new_w = csc(new_w, out_sharding)
            new_slots = tuple(csc(s, out_sharding) for s in new_slots)
        ret = [new_w, new_slots]
        if sentinel:
            sfin = jnp.isfinite(vals).all()[None].astype(jnp.float32)
            gnorm = jnp.sqrt(jnp.sum(
                jnp.square(gvals.astype(jnp.float32))))
            ret.append(jnp.concatenate([sfin, gnorm[None]]))
        if scaling:
            ret.append(fin)
        return tuple(ret)

    if not donate:
        return jax.jit(_executor._count_traces(step, "kv_sparse"))
    inner = jax.jit(_executor._count_traces(step, "kv_sparse"),
                    donate_argnums=(2, 3))

    def counted(idx_parts, val_parts, w, slots, lr, scale=None):
        if _tm.enabled():
            nbytes = int(w.size) * np.dtype(w.dtype).itemsize \
                + sum(int(s.size) * np.dtype(s.dtype).itemsize
                      for s in slots)
            _tm.health.donation_saved(nbytes, site="kv_sparse")
        if scale is None:
            return inner(idx_parts, val_parts, w, slots, lr)
        return inner(idx_parts, val_parts, w, slots, lr, scale)

    return counted


def _state_slots(state) -> Tuple[NDArray, ...]:
    if state is None:
        return ()
    if isinstance(state, (tuple, list)):
        return tuple(state)
    return (state,)


def concat_rows(values) -> RowSparseNDArray:
    """Merge a per-device list of row-sparse gradients into ONE
    uncoalesced pair (plain concatenation; the row-update program's
    in-trace segment-sum does the cross-device summing — the sparse
    analogue of Comm::Reduce)."""
    values = list(values)
    if len(values) == 1:
        return values[0]
    shape = values[0].shape
    for v in values[1:]:
        if v.shape != shape:
            raise MXNetError(
                f"row_sparse reduce: mismatched shapes {shape} vs "
                f"{v.shape}")
    idx = jnp.concatenate([v.indices._read() for v in values])
    vals = jnp.concatenate([v.data._read() for v in values])
    return RowSparseNDArray(NDArray(idx), NDArray(vals), shape)


# eager-path program cache: the eager fallback must NOT depend on the
# executor LRU being enabled (and must survive program_cache_clear in
# tests without changing math) — a small module-level dict suffices
_EAGER_PROGRAMS: Dict[tuple, object] = {}


def eager_update(optimizer, updater, index, weight: NDArray,
                 rs_grad: RowSparseNDArray):
    """Per-key row-sparse update for the eager paths (kvstore fallback
    loops, the Module-local Updater): same host bookkeeping as the
    dense eager update (update count, traced lr with bias correction,
    per-key wd), then the SAME jitted row program the fused sparse
    bucket runs — lazy-state semantics, bit-identical either way."""
    rule = optimizer.fused_rule() if optimizer is not None else None
    if rule is None:
        name = type(optimizer).__name__ if optimizer is not None \
            else "a custom updater"
        raise MXNetError(
            f"row_sparse gradients need an optimizer with a fused rule "
            f"(SGD/ccSGD/Adam/RMSProp); {name} must densify explicitly "
            f"via .todense()")
    rule_name, opt_params = rule
    optimizer._update_count(index)
    lr = float(optimizer.fused_lr(index))
    wd_mult = float(optimizer._get_wd(index))
    slots = _state_slots(updater.ensure_state(index, weight))
    # AMP fp32 master rows: the state's trailing slot is the master
    # table (optimizer.create_state) — the program must know
    mp = optimizer._use_master(weight)
    key = (rule_name, tuple(sorted(opt_params.items())), wd_mult, mp)
    fn = _EAGER_PROGRAMS.get(key)
    if fn is None:
        fn = make_row_program(rule_name, tuple(sorted(opt_params.items())),
                              wd_mult, nparts=1, mp=mp)
        _EAGER_PROGRAMS[key] = fn
    new_w, new_slots = fn(
        (rs_grad.indices._read(),), (rs_grad.data._read(),),
        weight._read(), tuple(s._read() for s in slots),
        np.float32(lr))
    weight._chunk.write(new_w)
    for s_nd, s_raw in zip(slots, new_slots):
        s_nd._chunk.write(s_raw)
