"""Verification utilities.

Parity: python/mxnet/test_utils.py (reference): check_numeric_gradient
(finite differences, :308), check_symbolic_forward/backward vs numpy
(:430,:491), check_consistency across contexts (:650 — reference checks
cpu-vs-gpu; here cpu(XLA-CPU)-vs-tpu, SURVEY.md §4.4), check_speed (:576).
"""
from __future__ import annotations

import time

import numpy as np

from . import ndarray as nd
from . import random as _random
from .context import Context, cpu, current_context
from .ndarray import NDArray


def default_context():
    return current_context()


# ---------------------------------------------------------------------------
# dtype-aware default tolerances (ISSUE-10 satellite).
#
# The old fp32-calibrated defaults made bf16 comparisons flaky: bf16
# carries ~8 mantissa bits (relative rounding ~2^-9 ≈ 2e-3), fp16 ~11.
# The reference's check_consistency keys tolerance on dtype the same
# way (test_utils.py:650 tol tables).
# ---------------------------------------------------------------------------
_DTYPE_RTOL_ATOL = {
    np.dtype(np.float64): (1e-7, 1e-9),
    np.dtype(np.float32): (1e-5, 1e-8),
    np.dtype(np.float16): (1e-2, 1e-3),
}


def _tols_for_dtype(dtype):
    """(rtol, atol) for one dtype; None for non-floats."""
    if dtype is None:
        return None
    if "bfloat16" in str(dtype):
        return 3e-2, 1e-2
    try:
        return _DTYPE_RTOL_ATOL.get(np.dtype(dtype))
    except TypeError:
        return None


def default_tols(*arrays, rtol=None, atol=None):
    """(rtol, atol) for comparing ``arrays``: explicit values win;
    otherwise the WIDEST tolerance among the operands' dtypes (bf16
    included — jnp.bfloat16 has no numpy literal, matched by name)."""
    if rtol is not None and atol is not None:
        return rtol, atol
    pick_r, pick_a = _DTYPE_RTOL_ATOL[np.dtype(np.float32)]
    for a in arrays:
        tols = _tols_for_dtype(getattr(a, "dtype", None))
        if tols is not None and tols[0] > pick_r:
            pick_r, pick_a = tols
    return (rtol if rtol is not None else pick_r,
            atol if atol is not None else pick_a)


def _as_numpy_dict(symbol, location):
    args = symbol.list_arguments()
    if isinstance(location, dict):
        return {k: np.asarray(v, dtype=np.float32) for k, v in location.items()}
    return {k: np.asarray(v, dtype=np.float32) for k, v in zip(args, location)}


def _bind_with(symbol, location, aux=None, grad_req="write", ctx=None):
    ctx = ctx or default_context()
    ex = symbol.simple_bind(ctx, grad_req=grad_req,
                            **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        ex.arg_dict[k][:] = v
    for k, v in (aux or {}).items():
        ex.aux_dict[k][:] = v
    return ex


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None):
    """Parity: test_utils.check_symbolic_forward (:430)."""
    location = _as_numpy_dict(sym, location)
    ex = _bind_with(sym, location, aux_states, grad_req="null", ctx=ctx)
    outputs = ex.forward(is_train=False)
    if isinstance(expected, (list, tuple)):
        pairs = zip(outputs, expected)
    else:
        pairs = [(outputs[0], expected)]
    for out, exp in pairs:
        np.testing.assert_allclose(out.asnumpy(), exp, rtol=rtol, atol=atol)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-5, aux_states=None, grad_req="write", ctx=None):
    """Parity: test_utils.check_symbolic_backward (:491)."""
    location = _as_numpy_dict(sym, location)
    ex = _bind_with(sym, location, aux_states, grad_req=grad_req, ctx=ctx)
    ex.forward(is_train=True)
    og = None
    if out_grads is not None:
        og = [nd.array(np.asarray(g, dtype=np.float32)) for g in out_grads]
    ex.backward(og)
    if isinstance(expected, dict):
        for name, exp in expected.items():
            np.testing.assert_allclose(ex.grad_dict[name].asnumpy(), exp,
                                       rtol=rtol, atol=atol, err_msg=name)
    else:
        for name, exp in zip(sym.list_arguments(), expected):
            if exp is None:
                continue
            np.testing.assert_allclose(ex.grad_dict[name].asnumpy(), exp,
                                       rtol=rtol, atol=atol, err_msg=name)
    return {k: v.asnumpy() for k, v in ex.grad_dict.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None):
    """Finite-difference gradient check (parity: test_utils.py:308).

    Uses sum-of-outputs as the implicit scalar loss: backward() is called
    with all-ones head gradients matching the reference helper's behavior.
    """
    location = _as_numpy_dict(sym, location)
    grad_nodes = grad_nodes or list(location.keys())
    ex = _bind_with(sym, location, aux_states, grad_req="write", ctx=ctx)
    ex.forward(is_train=True)
    out_shapes = [o.shape for o in ex.outputs]
    ex.backward([nd.ones(s) for s in out_shapes])
    analytic = {k: ex.grad_dict[k].asnumpy().copy() for k in grad_nodes
                if k in ex.grad_dict}

    def loss_at(loc):
        ex2 = _bind_with(sym, loc, aux_states, grad_req="null", ctx=ctx)
        outs = ex2.forward(is_train=True)
        return sum(float(o.asnumpy().sum()) for o in outs)

    for name in grad_nodes:
        if name not in analytic:
            continue
        base = location[name]
        num_grad = np.zeros_like(base)
        flat = base.reshape(-1)
        ng = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            lp = loss_at(location)
            flat[i] = orig - numeric_eps
            lm = loss_at(location)
            flat[i] = orig
            ng[i] = (lp - lm) / (2 * numeric_eps)
        np.testing.assert_allclose(analytic[name], num_grad, rtol=rtol,
                                   atol=atol or 1e-2, err_msg=name)


def check_consistency(sym, ctx_list, scale=1.0, rtol=None, atol=None,
                      arg_params=None, amp=None):
    """Run the same symbol on several contexts and cross-check outputs+grads
    (parity: test_utils.check_consistency :650 — the cpu/gpu harness that
    becomes cpu/tpu on this stack).  arg_params overrides the random fill
    for specific args (e.g. integer Embedding indices).

    rtol/atol left None pick dtype-aware defaults: a spec whose
    ``type_dict`` (or ``amp='bf16'``) puts bfloat16 in play compares at
    bf16 tolerance instead of the fp32-calibrated 1e-3/1e-4.  ``amp``
    sets ``MXTPU_AMP`` for the whole run (every context binds through
    the amp_cast pass), so a single call cross-checks the AMP numerics
    of cpu-vs-tpu the way the reference harness cross-checks
    cpu-vs-gpu."""
    import os

    low_prec = amp is not None and str(amp) not in ("0", "off", "False")
    for spec in ctx_list:
        for dt in (spec.get("type_dict") or {}).values():
            if "float16" in str(np.dtype(dt) if dt is not None else ""):
                low_prec = True
    if rtol is None and atol is None and low_prec:
        rtol, atol = 3e-2, 1e-2
    elif rtol is None or atol is None:
        rtol = 1e-3 if rtol is None else rtol
        atol = 1e-4 if atol is None else atol

    prev_amp = os.environ.get("MXTPU_AMP")
    if amp is not None:
        os.environ["MXTPU_AMP"] = str(amp)
    try:
        results = []
        for spec in ctx_list:
            ctx = spec["ctx"]
            shapes = {k: v for k, v in spec.items() if k != "ctx" and k != "type_dict"}
            _random.seed(0)
            ex = sym.simple_bind(ctx, grad_req="write",
                                 type_dict=spec.get("type_dict"), **shapes)
            rs = np.random.RandomState(0)
            for k in sorted(ex.arg_dict):
                if arg_params and k in arg_params:
                    ex.arg_dict[k][:] = np.asarray(arg_params[k], np.float32)
                    continue
                ex.arg_dict[k][:] = (rs.standard_normal(ex.arg_dict[k].shape) * scale).astype(np.float32)
            ex.forward(is_train=True)
            ex.backward([nd.ones(o.shape) for o in ex.outputs])
            results.append((
                [o.asnumpy() for o in ex.outputs],
                {k: v.asnumpy() for k, v in ex.grad_dict.items()},
            ))
    finally:
        if amp is not None:
            if prev_amp is None:
                os.environ.pop("MXTPU_AMP", None)
            else:
                os.environ["MXTPU_AMP"] = prev_amp
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for a, b in zip(ref_outs, outs):
            np.testing.assert_allclose(np.asarray(a, np.float64),
                                       np.asarray(b, np.float64),
                                       rtol=rtol, atol=atol)
        for k in ref_grads:
            np.testing.assert_allclose(np.asarray(ref_grads[k], np.float64),
                                       np.asarray(grads[k], np.float64),
                                       rtol=rtol, atol=atol, err_msg=k)
    return results


def check_speed(sym, location=None, ctx=None, n=20, grad_req="write", **shapes):
    """Parity: test_utils.check_speed (:576) — seconds per fwd+bwd."""
    ctx = ctx or default_context()
    if location is None:
        ex = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
        rs = np.random.RandomState(0)
        for k in ex.arg_dict:
            ex.arg_dict[k][:] = rs.standard_normal(ex.arg_dict[k].shape).astype(np.float32)
    else:
        location = _as_numpy_dict(sym, location)
        ex = _bind_with(sym, location, grad_req=grad_req, ctx=ctx)
    # warmup (compile)
    ex.forward(is_train=True)
    ex.backward()
    [o.wait_to_read() for o in ex.outputs]
    tic = time.time()
    for _ in range(n):
        ex.forward(is_train=True)
        ex.backward()
    [o.wait_to_read() for o in ex.outputs]
    for g in ex.grad_dict.values():
        g.wait_to_read()
    return (time.time() - tic) / n


def rand_ndarray(shape, ctx=None):
    return nd.array(np.random.uniform(-1, 1, shape).astype(np.float32), ctx=ctx)


def same(a, b):
    return np.array_equal(a, b)


def almost_equal(a, b, rtol=None, atol=None):
    rtol, atol = default_tols(a, b, rtol=rtol, atol=atol)
    return np.allclose(np.asarray(a, np.float64), np.asarray(b, np.float64),
                       rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    """Parity: test_utils.assert_almost_equal — with rtol/atol left
    None, the defaults come from the operands' dtypes (bfloat16 gets
    ~2^-9-relative slack instead of the fp32-calibrated 1e-5 that made
    bf16 comparisons flaky)."""
    rtol, atol = default_tols(a, b, rtol=rtol, atol=atol)
    np.testing.assert_allclose(np.asarray(a, np.float64),
                               np.asarray(b, np.float64),
                               rtol=rtol, atol=atol)


def get_synthetic_mnist(num_train=512, num_test=128, seed=7):
    """Deterministic MNIST-like dataset (no network egress in this image;
    the reference's tests download real MNIST via get_data.py).  Classes are
    linearly separable blobs rendered into 1x28x28 images so small models
    reach high accuracy within a few epochs."""
    rs = np.random.RandomState(seed)
    n = num_train + num_test
    labels = rs.randint(0, 10, size=n)
    images = np.zeros((n, 1, 28, 28), dtype=np.float32)
    # each class lights a distinct 6x6 block (plus noise)
    for i, lab in enumerate(labels):
        r, c = divmod(int(lab), 5)
        images[i, 0, 2 + r * 13 : 8 + r * 13, 1 + c * 5 : 7 + c * 5] = 1.0
    images += rs.uniform(0, 0.3, images.shape).astype(np.float32)
    x_train, x_test = images[:num_train], images[num_train:]
    y_train, y_test = labels[:num_train].astype(np.float32), labels[num_train:].astype(np.float32)
    return (x_train, y_train), (x_test, y_test)
