"""Verification utilities.

Parity: python/mxnet/test_utils.py (reference): check_numeric_gradient
(finite differences, :308), check_symbolic_forward/backward vs numpy
(:430,:491), check_consistency across contexts (:650 — reference checks
cpu-vs-gpu; here cpu(XLA-CPU)-vs-tpu, SURVEY.md §4.4), check_speed (:576).
"""
from __future__ import annotations

import time

import numpy as np

from . import ndarray as nd
from . import random as _random
from .context import Context, cpu, current_context
from .ndarray import NDArray


def default_context():
    return current_context()


def _as_numpy_dict(symbol, location):
    args = symbol.list_arguments()
    if isinstance(location, dict):
        return {k: np.asarray(v, dtype=np.float32) for k, v in location.items()}
    return {k: np.asarray(v, dtype=np.float32) for k, v in zip(args, location)}


def _bind_with(symbol, location, aux=None, grad_req="write", ctx=None):
    ctx = ctx or default_context()
    ex = symbol.simple_bind(ctx, grad_req=grad_req,
                            **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        ex.arg_dict[k][:] = v
    for k, v in (aux or {}).items():
        ex.aux_dict[k][:] = v
    return ex


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None):
    """Parity: test_utils.check_symbolic_forward (:430)."""
    location = _as_numpy_dict(sym, location)
    ex = _bind_with(sym, location, aux_states, grad_req="null", ctx=ctx)
    outputs = ex.forward(is_train=False)
    if isinstance(expected, (list, tuple)):
        pairs = zip(outputs, expected)
    else:
        pairs = [(outputs[0], expected)]
    for out, exp in pairs:
        np.testing.assert_allclose(out.asnumpy(), exp, rtol=rtol, atol=atol)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-5, aux_states=None, grad_req="write", ctx=None):
    """Parity: test_utils.check_symbolic_backward (:491)."""
    location = _as_numpy_dict(sym, location)
    ex = _bind_with(sym, location, aux_states, grad_req=grad_req, ctx=ctx)
    ex.forward(is_train=True)
    og = None
    if out_grads is not None:
        og = [nd.array(np.asarray(g, dtype=np.float32)) for g in out_grads]
    ex.backward(og)
    if isinstance(expected, dict):
        for name, exp in expected.items():
            np.testing.assert_allclose(ex.grad_dict[name].asnumpy(), exp,
                                       rtol=rtol, atol=atol, err_msg=name)
    else:
        for name, exp in zip(sym.list_arguments(), expected):
            if exp is None:
                continue
            np.testing.assert_allclose(ex.grad_dict[name].asnumpy(), exp,
                                       rtol=rtol, atol=atol, err_msg=name)
    return {k: v.asnumpy() for k, v in ex.grad_dict.items()}


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None):
    """Finite-difference gradient check (parity: test_utils.py:308).

    Uses sum-of-outputs as the implicit scalar loss: backward() is called
    with all-ones head gradients matching the reference helper's behavior.
    """
    location = _as_numpy_dict(sym, location)
    grad_nodes = grad_nodes or list(location.keys())
    ex = _bind_with(sym, location, aux_states, grad_req="write", ctx=ctx)
    ex.forward(is_train=True)
    out_shapes = [o.shape for o in ex.outputs]
    ex.backward([nd.ones(s) for s in out_shapes])
    analytic = {k: ex.grad_dict[k].asnumpy().copy() for k in grad_nodes
                if k in ex.grad_dict}

    def loss_at(loc):
        ex2 = _bind_with(sym, loc, aux_states, grad_req="null", ctx=ctx)
        outs = ex2.forward(is_train=True)
        return sum(float(o.asnumpy().sum()) for o in outs)

    for name in grad_nodes:
        if name not in analytic:
            continue
        base = location[name]
        num_grad = np.zeros_like(base)
        flat = base.reshape(-1)
        ng = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            lp = loss_at(location)
            flat[i] = orig - numeric_eps
            lm = loss_at(location)
            flat[i] = orig
            ng[i] = (lp - lm) / (2 * numeric_eps)
        np.testing.assert_allclose(analytic[name], num_grad, rtol=rtol,
                                   atol=atol or 1e-2, err_msg=name)


def check_consistency(sym, ctx_list, scale=1.0, rtol=1e-3, atol=1e-4,
                      arg_params=None):
    """Run the same symbol on several contexts and cross-check outputs+grads
    (parity: test_utils.check_consistency :650 — the cpu/gpu harness that
    becomes cpu/tpu on this stack).  arg_params overrides the random fill
    for specific args (e.g. integer Embedding indices)."""
    results = []
    for spec in ctx_list:
        ctx = spec["ctx"]
        shapes = {k: v for k, v in spec.items() if k != "ctx" and k != "type_dict"}
        _random.seed(0)
        ex = sym.simple_bind(ctx, grad_req="write",
                             type_dict=spec.get("type_dict"), **shapes)
        rs = np.random.RandomState(0)
        for k in sorted(ex.arg_dict):
            if arg_params and k in arg_params:
                ex.arg_dict[k][:] = np.asarray(arg_params[k], np.float32)
                continue
            ex.arg_dict[k][:] = (rs.standard_normal(ex.arg_dict[k].shape) * scale).astype(np.float32)
        ex.forward(is_train=True)
        ex.backward([nd.ones(o.shape) for o in ex.outputs])
        results.append((
            [o.asnumpy() for o in ex.outputs],
            {k: v.asnumpy() for k, v in ex.grad_dict.items()},
        ))
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for a, b in zip(ref_outs, outs):
            np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)
        for k in ref_grads:
            np.testing.assert_allclose(ref_grads[k], grads[k], rtol=rtol,
                                       atol=atol, err_msg=k)
    return results


def check_speed(sym, location=None, ctx=None, n=20, grad_req="write", **shapes):
    """Parity: test_utils.check_speed (:576) — seconds per fwd+bwd."""
    ctx = ctx or default_context()
    if location is None:
        ex = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
        rs = np.random.RandomState(0)
        for k in ex.arg_dict:
            ex.arg_dict[k][:] = rs.standard_normal(ex.arg_dict[k].shape).astype(np.float32)
    else:
        location = _as_numpy_dict(sym, location)
        ex = _bind_with(sym, location, grad_req=grad_req, ctx=ctx)
    # warmup (compile)
    ex.forward(is_train=True)
    ex.backward()
    [o.wait_to_read() for o in ex.outputs]
    tic = time.time()
    for _ in range(n):
        ex.forward(is_train=True)
        ex.backward()
    [o.wait_to_read() for o in ex.outputs]
    for g in ex.grad_dict.values():
        g.wait_to_read()
    return (time.time() - tic) / n


def rand_ndarray(shape, ctx=None):
    return nd.array(np.random.uniform(-1, 1, shape).astype(np.float32), ctx=ctx)


def same(a, b):
    return np.array_equal(a, b)


def almost_equal(a, b, rtol=1e-5, atol=1e-8):
    return np.allclose(a, b, rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)


def get_synthetic_mnist(num_train=512, num_test=128, seed=7):
    """Deterministic MNIST-like dataset (no network egress in this image;
    the reference's tests download real MNIST via get_data.py).  Classes are
    linearly separable blobs rendered into 1x28x28 images so small models
    reach high accuracy within a few epochs."""
    rs = np.random.RandomState(seed)
    n = num_train + num_test
    labels = rs.randint(0, 10, size=n)
    images = np.zeros((n, 1, 28, 28), dtype=np.float32)
    # each class lights a distinct 6x6 block (plus noise)
    for i, lab in enumerate(labels):
        r, c = divmod(int(lab), 5)
        images[i, 0, 2 + r * 13 : 8 + r * 13, 1 + c * 5 : 7 + c * 5] = 1.0
    images += rs.uniform(0, 0.3, images.shape).astype(np.float32)
    x_train, x_test = images[:num_train], images[num_train:]
    y_train, y_test = labels[:num_train].astype(np.float32), labels[num_train:].astype(np.float32)
    return (x_train, y_train), (x_test, y_test)
