"""Resource manager — per-context shared op resources.

Parity: include/mxnet/resource.h (ResourceRequest/Resource/ResourceManager)
and src/executor/attach_op_resource_pass.cc.  The reference hands ops two
resource kinds:

- ``kRandom``: a per-device PRNG stream.  Compiled ops here get pure,
  replayable subkeys through ``OpCtx.rng()`` (ops/registry.py) — that path
  IS the kRandom equivalent and needs no manager.  This module serves the
  host-side consumers (custom ops, data pipeline) with seeded
  ``numpy.random.Generator`` streams.
- ``kTempSpace``: resizable scratch memory shared between ops to bound
  allocator churn.  On TPU the compiled graph's scratch is XLA's problem
  (buffer assignment), but host-side custom ops (operator.py CustomOp,
  plugins) still want reusable pinned scratch: here temp space is backed
  by the native host arena (src/storage.cc) when available, plain numpy
  otherwise.  ``MXNET_EXEC_NUM_TEMP`` bounds the number of concurrently
  cached spaces per context, like the reference's round-robin limit
  (docs/how_to/env_var.md).
"""
from __future__ import annotations

import threading

import numpy as np

from .base import MXNetError, get_env


class ResourceRequest:
    """Parity: ResourceRequest::Type (resource.h:18-36)."""

    kRandom = "random"
    kTempSpace = "temp_space"

    def __init__(self, type):  # noqa: A002 - reference field name
        if type not in (self.kRandom, self.kTempSpace):
            raise MXNetError(f"unknown resource type {type!r}")
        self.type = type

    def __repr__(self):
        return f"ResourceRequest({self.type})"


class Resource:
    """A granted resource (parity: resource.h Resource).

    For kTempSpace, ``get_space(shape, dtype)`` returns scratch that is
    REUSED across calls (contents undefined, like the reference's
    workspace); for kRandom, ``generator()`` returns the seeded stream
    and ``seed(n)`` reseeds it.
    """

    def __init__(self, req, ctx, slot):
        self.req = req
        self.ctx = ctx
        self._slot = slot
        self._lock = threading.Lock()
        if req.type == ResourceRequest.kRandom:
            self._gen = np.random.default_rng(0)
        else:
            self._buf = None  # grown on demand, never shrunk
            self._buf_native = False

    # ------------------------------------------------------------- kRandom
    def generator(self):
        if self.req.type != ResourceRequest.kRandom:
            raise MXNetError("not a random resource")
        return self._gen

    def seed(self, seed):
        self._gen = np.random.default_rng(seed)

    # ---------------------------------------------------------- kTempSpace
    def get_space(self, shape, dtype=np.float32):
        """Scratch ndarray of `shape`; grows the backing block as needed.
        Parity: Resource::get_space (resource.h:84-100)."""
        if self.req.type != ResourceRequest.kTempSpace:
            raise MXNetError("not a temp_space resource")
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize if shape else dtype.itemsize
        with self._lock:
            if self._buf is None or self._buf.nbytes < nbytes:
                arena = _get_arena()
                if self._buf is not None and arena is not None \
                        and self._buf_native:
                    arena.free(self._buf)  # recycle into the size-class pool
                if arena is not None:
                    try:
                        self._buf = arena.alloc((nbytes,), np.uint8)
                        self._buf_native = True
                    except Exception:  # noqa: BLE001 — fallback contract
                        self._buf = np.empty(nbytes, np.uint8)
                        self._buf_native = False
                else:
                    self._buf = np.empty(nbytes, np.uint8)
                    self._buf_native = False
            flat = self._buf[:nbytes].view(dtype)
        return flat[: int(np.prod(shape))].reshape(shape)


_ARENA = None  # shared NativeArena handle; False = unavailable


def _get_arena():
    """Backing storage for temp spaces: the native host arena when built
    (so grown-away blocks recycle through its pool), else None."""
    global _ARENA
    if _ARENA is False:
        return None
    if _ARENA is None:
        try:
            from . import _native

            _ARENA = _native.NativeArena()
        except Exception:  # noqa: BLE001 — graceful fallback is the contract
            _ARENA = False
            return None
    return _ARENA


class ResourceManager:
    """Per-context resource registry (parity: ResourceManager::Get()).

    Temp spaces are handed out round-robin over MXNET_EXEC_NUM_TEMP slots
    (default 1, like the reference) so at most that many scratch blocks
    exist per context.
    """

    _instance = None
    _ilock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._temp = {}  # ctx str -> [Resource]
        self._rand = {}  # ctx str -> Resource
        self._rr = {}

    @classmethod
    def get(cls):
        with cls._ilock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def request(self, ctx, req):
        if isinstance(req, str):
            req = ResourceRequest(req)
        key = str(ctx)
        with self._lock:
            if req.type == ResourceRequest.kRandom:
                if key not in self._rand:
                    self._rand[key] = Resource(req, ctx, 0)
                return self._rand[key]
            num = max(1, int(get_env("MXNET_EXEC_NUM_TEMP", 1)))
            slots = self._temp.setdefault(key, [])
            if len(slots) < num:
                slots.append(Resource(req, ctx, len(slots)))
                return slots[-1]
            self._rr[key] = (self._rr.get(key, -1) + 1) % num
            return slots[self._rr[key]]

    def seed_random(self, seed):
        """Parity: MXRandomSeed seeding every device's kRandom stream."""
        with self._lock:
            for r in self._rand.values():
                # lock-ok: r is a Resource kRandom stream whose seed() is
                # a plain numpy reseed; the lint's virtual dispatch also
                # matches random.seed (which re-enters this manager), but
                # that callee cannot be reached from a Resource value
                r.seed(seed)
