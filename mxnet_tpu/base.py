"""Common utilities for mxnet_tpu.

TPU-native re-imagining of MXNet's dmlc-core utility surface
(reference: include/mxnet/base.h, dmlc logging/parameter).  There is no C
ABI boundary here: the "C API" layer of the reference (src/c_api/) is
collapsed into the Python package because the compute substrate is
JAX/XLA, reached directly through jaxlib.
"""
from __future__ import annotations

import ast
import os
from typing import Any

__version__ = "0.1.0"


class MXNetError(RuntimeError):
    """Error raised by mxnet_tpu (parity: dmlc::Error / MXGetLastError)."""


def get_env(name: str, default, dtype=None):
    """Read an env var with a typed default (parity: dmlc::GetEnv).

    Environment variables keep their reference names (MXNET_*) so existing
    user configs carry over; see docs/how_to/env_var.md in the reference.
    """
    val = os.environ.get(name)
    if val is None:
        return default
    ty = dtype or type(default)
    if ty is bool:
        return val.lower() not in ("0", "false", "")
    return ty(val)


def parse_attr(value: Any):
    """Normalize an op attribute that may arrive as a string.

    The reference parses all op kwargs from strings via dmlc::Parameter
    (include/mxnet/base.h + dmlc parameter.h); frontends send everything
    as str through the C API.  We accept native Python values but also
    literal-parse strings so string-typed configs behave identically.
    """
    if not isinstance(value, str):
        return value
    s = value.strip()
    low = s.lower()
    if low in ("true",):
        return True
    if low in ("false",):
        return False
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return value


def normalize_tuple(value, ndim: int, name: str = "value"):
    """Broadcast an int (or 1-tuple) to an ndim-tuple (kernel/stride/pad)."""
    value = parse_attr(value)
    if isinstance(value, int):
        return (value,) * ndim
    value = tuple(value)
    if len(value) == 1:
        return value * ndim
    if len(value) != ndim:
        raise ValueError(f"{name} must have {ndim} elements, got {value}")
    return value


_BOOL_STRS = {"true": True, "false": False, "1": True, "0": False}


def parse_bool(value) -> bool:
    if isinstance(value, str):
        return _BOOL_STRS.get(value.lower(), bool(value))
    return bool(value)


def frozen_attrs(attrs: dict) -> tuple:
    """Hashable view of an attr dict, for jit-dispatch cache keys."""

    def freeze(v):
        if isinstance(v, (list, tuple)):
            return tuple(freeze(x) for x in v)
        if isinstance(v, dict):
            return tuple(sorted((k, freeze(x)) for k, x in v.items()))
        return v

    return tuple(sorted((k, freeze(v)) for k, v in attrs.items()))


class _NameManager:
    """Auto-namer for symbols (parity: python/mxnet/name.py NameManager)."""

    _current = None

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name:
            return name
        hint = hint.lower()
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        self._old = _NameManager._current
        _NameManager._current = self
        return self

    def __exit__(self, *exc):
        _NameManager._current = self._old


def current_name_manager() -> _NameManager:
    if _NameManager._current is None:
        _NameManager._current = _NameManager()
    return _NameManager._current


NameManager = _NameManager


class AttrScope:
    """Scoped symbol attributes (parity: python/mxnet/attribute.py).

    Used for model parallelism: ``with mx.AttrScope(ctx_group='dev1'):``
    tags symbols; the executor maps groups to mesh shardings
    (reference: graph_executor.cc:225-314 PlaceDevice pass).
    """

    _current = None

    def __init__(self, **kwargs):
        self._attr = {k: str(v) for k, v in kwargs.items()}

    def get(self, attr):
        merged = dict(self._attr)
        if attr:
            merged.update(attr)
        return merged

    def __enter__(self):
        self._old = AttrScope._current
        if self._old is not None:
            merged = dict(self._old._attr)
            merged.update(self._attr)
            scope = AttrScope()
            scope._attr = merged
            AttrScope._current = scope
        else:
            AttrScope._current = self
        return self

    def __exit__(self, *exc):
        AttrScope._current = self._old


def current_attr_scope():
    return AttrScope._current


def mxu_precision(*arrays):
    """Per-op matmul precision: single-pass MXU for low-precision inputs.

    The package default (jax_default_matmul_precision=float32, __init__.py)
    gives fp32 arrays reference-parity fp32 math — but that global knob
    would ALSO make explicit bfloat16/fp16 data run multi-pass emulated
    matmuls, wasting the MXU fast path.  Hot ops pass
    ``precision=mxu_precision(x, w)``: lax.Precision.DEFAULT (one MXU pass)
    when any operand is already low-precision, None (defer to the global
    fp32 policy) otherwise.
    """
    import jax

    low = (("bfloat16", "float16"))
    for a in arrays:
        dt = getattr(a, "dtype", None)
        if dt is not None and str(dt) in low:
            return jax.lax.Precision.DEFAULT
    return None


_conv_precision_warned = False


def conv_precision(*arrays):
    """Per-op precision for CONVOLUTIONS: single MXU pass unless opted out.

    Convs deliberately do NOT inherit the fp32 multi-pass policy that
    matmuls get from ``jax_default_matmul_precision=float32``:

    - XLA:TPU lowers a multi-pass (bf16x3/x6 emulated-fp32) convolution
      through a rewrite whose compile time blows up superlinearly in
      spatial size — measured on v5e: a single f32 5x5 conv on
      (128,1,28,28) compiles in ~27 s single-pass but did not finish in
      >8 min at HIGH or HIGHEST (forward alone), while 16x16 still
      compiled in ~70 s.  Training-shaped conv nets in fp32 were
      effectively uncompilable.
    - bf16 inputs with fp32 accumulation is the canonical TPU conv path;
      consistency vs fp32 reference math holds to a few 1e-2
      (tests/test_tpu_consistency.py gates conv families at 6e-2).

    ``MXTPU_CONV_PRECISION=float32`` (or ``highest``/``high``) restores
    emulated wide-precision convs for small-shape use (the pre-rename
    spelling ``MXNET_TPU_CONV_PRECISION`` is still accepted).  Because
    the reduced default silently changes fp32 conv numerics vs the
    reference (drift up to ~5e-2), the first fp32 conv lowered at
    reduced precision emits a one-time warning naming the knob.
    """
    import jax

    pref = os.environ.get(
        "MXTPU_CONV_PRECISION",
        os.environ.get("MXNET_TPU_CONV_PRECISION", "")).lower()
    if pref in ("float32", "highest"):
        return jax.lax.Precision.HIGHEST
    if pref in ("high", "bfloat16_3x", "tensorfloat32"):
        return jax.lax.Precision.HIGH
    # trace-ok: warn-once latch flips at trace time on purpose; the
    # compiled program is unaffected and retraces stay silent
    global _conv_precision_warned
    if not _conv_precision_warned and any(
            str(getattr(a, "dtype", "")) == "float32" for a in arrays):
        _conv_precision_warned = True
        import warnings

        warnings.warn(
            "fp32 convolution lowered at reduced precision (single-pass "
            "bf16-input MXU math; drift vs true-fp32 up to ~5e-2).  Set "
            "MXTPU_CONV_PRECISION=float32 to restore emulated wide-"
            "precision convs (slow/uncompilable at training shapes).",
            stacklevel=2)
    return jax.lax.Precision.DEFAULT
