"""RecordIO file format.

Parity: python/mxnet/recordio.py + dmlc-core RecordIO (reference).  Binary
format kept bit-compatible with the reference so existing .rec datasets
load unchanged: records framed by magic 0xced7230a + length word, payload
padded to 4 bytes (dmlc/recordio.h framing); IRHeader packs
(flag, label, id, id2) as <IfQQ (python/mxnet/recordio.py:176 IRHeader).
MXIndexedRecordIO keeps the .idx tell-offset sidecar.
"""
from __future__ import annotations

import numbers
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

_MAGIC = 0xCED7230A
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential RecordIO reader/writer (parity: recordio.py:22)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.fp = None
        self.is_open = False
        self.open()

    def open(self):
        # URIs route through the filesystem registry (parity: dmlc
        # Stream::Create) — local paths behave exactly as before, and
        # mem:// / registered remote schemes work transparently
        from .filesystem import open_uri

        if self.flag == "w":
            self.fp = open_uri(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fp = open_uri(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("invalid flag " + self.flag)
        self.is_open = True

    def close(self):
        if self.is_open and self.fp:
            self.fp.close()
            self.is_open = False

    def __del__(self):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes):
        assert self.writable
        length = len(buf)
        self.fp.write(struct.pack("<II", _MAGIC, length))
        self.fp.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.fp.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        header = self.fp.read(8)
        if len(header) < 8:
            return None
        magic, length = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError(f"invalid RecordIO magic {magic:#x} in {self.uri}")
        buf = self.fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fp.read(pad)
        return buf

    def tell(self):
        return self.fp.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with .idx sidecar (parity: recordio.py:103)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    key, pos = line.strip().split("\t")
                    key = self.key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.fp.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Parity: recordio.py pack (:176) — header(+vector label) + payload."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        out = struct.pack(_IR_FORMAT, header.flag, header.label, header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        out = struct.pack(_IR_FORMAT, header.flag, header.label, header.id, header.id2)
        out += label.tobytes()
    return out + s


def unpack(s: bytes):
    """Parity: recordio.py unpack (:210)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(payload[: header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        payload = payload[header.flag * 4 :]
    return header, payload


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Parity: recordio.py pack_img — encodes with Pillow if available,
    else raw npy bytes (the decode side mirrors this)."""
    from .image import imencode

    return pack(header, imencode(img, quality=quality, img_fmt=img_fmt))


def unpack_img(s, iscolor=-1):
    """Parity: recordio.py unpack_img."""
    from .image import imdecode_np

    header, payload = unpack(s)
    return header, imdecode_np(payload)
