"""Evaluation metrics (parity: python/mxnet/metric.py).

EvalMetric registry: Accuracy, TopKAccuracy, F1, MAE/MSE/RMSE,
CrossEntropy, CustomMetric (+np wrapper), CompositeEvalMetric.  Metrics
run on host numpy after a device sync — same device→host boundary as the
reference (SURVEY.md §3.1 update_metric step).
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError


def check_label_shapes(labels, preds, shape=False):
    if len(labels) != len(preds):
        raise MXNetError(f"label/pred count mismatch: {len(labels)} vs {len(preds)}")


class EvalMetric:
    """Base metric with a local/global accumulator split.

    Subclasses only ever touch ``sum_metric``/``num_inst`` (the *local*
    window).  ``reset_local()`` folds the window into carried totals and
    clears it — so interval reporters (Speedometer auto_reset) can print
    per-window values while ``get_global_name_value()`` still returns the
    true since-``reset()`` aggregate for epoch-end logs.  (The v0.9.4
    reference lacks this split and its epoch log after an auto_reset
    Speedometer covers only the tail window; later MXNet added
    reset_local/get_global, which is the behavior reproduced here.)
    """

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
            self._carried_num = 0
            self._carried_sum = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num
            self._carried_num = [0] * self.num
            self._carried_sum = [0.0] * self.num

    def reset_local(self):
        """Fold the current window into the global totals and clear it."""
        if self.num is None:
            self._carried_num += self.num_inst
            self._carried_sum += self.sum_metric
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            for i in range(self.num):
                self._carried_num[i] += self.num_inst[i]
                self._carried_sum[i] += self.sum_metric[i]
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def update(self, labels, preds):
        raise NotImplementedError

    def _value(self, s, n):
        """Accumulators -> reported value; metrics with a non-mean readout
        (e.g. Perplexity's exp) override THIS so local and global views
        stay consistent."""
        return s / n if n else float("nan")

    def get(self):
        if self.num is None:
            return (self.name, self._value(self.sum_metric, self.num_inst))
        names = [f"{self.name}_{i}" for i in range(self.num)]
        values = [self._value(s, n)
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_global(self):
        if self.num is None:
            return (self.name, self._value(self._carried_sum + self.sum_metric,
                                           self._carried_num + self.num_inst))
        names = [f"{self.name}_{i}" for i in range(self.num)]
        values = [
            self._value(cs + s, cn + n)
            for cs, s, cn, n in zip(self._carried_sum, self.sum_metric,
                                    self._carried_num, self.num_inst)
        ]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))

    def get_global_name_value(self):
        name, value = self.get_global()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite"):
        super().__init__(name)
        self.metrics = metrics or []

    def add(self, metric):
        self.metrics.append(metric)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def reset_local(self):
        for m in self.metrics:
            m.reset_local()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)

    def get_global(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get_global()
            names.append(n)
            values.append(v)
        return (names, values)


class Accuracy(EvalMetric):
    """Parity: metric.py Accuracy — argmax over axis 1 when needed.

    ``ignore_label`` drops masked entries (padding frames in bucketed
    sequence training) from both numerator and denominator, pairing with
    SoftmaxOutput(use_ignore=True)."""

    def __init__(self, ignore_label=None):
        super().__init__("accuracy")
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred_np = pred.asnumpy()
            label_np = label.asnumpy().astype(_np.int32)
            if pred_np.ndim > 1 and pred_np.shape != label_np.shape:
                pred_np = pred_np.argmax(axis=1)
            pred_np = pred_np.astype(_np.int32).reshape(-1)
            label_np = label_np.reshape(-1)
            if self.ignore_label is not None:
                keep = label_np != self.ignore_label
                pred_np, label_np = pred_np[keep], label_np[keep]
            self.sum_metric += float((pred_np == label_np).sum())
            self.num_inst += len(label_np)


class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1):
        super().__init__(f"top_k_accuracy_{top_k}")
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred_np = pred.asnumpy()
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            argsorted = _np.argsort(-pred_np, axis=1)[:, : self.top_k]
            self.sum_metric += float((argsorted == label_np[:, None]).any(axis=1).sum())
            self.num_inst += len(label_np)


class F1(EvalMetric):
    """Binary F1 (parity: metric.py F1)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred_np = pred.asnumpy()
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            if pred_np.ndim > 1:
                pred_np = pred_np.argmax(axis=1)
            pred_np = pred_np.astype(_np.int32).reshape(-1)
            tp = float(((pred_np == 1) & (label_np == 1)).sum())
            fp = float(((pred_np == 1) & (label_np == 0)).sum())
            fn = float(((pred_np == 0) & (label_np == 1)).sum())
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            f1 = 2 * precision * recall / (precision + recall) if precision + recall > 0 else 0.0
            self.sum_metric += f1
            self.num_inst += 1


class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = label.asnumpy(), pred.asnumpy()
            self.sum_metric += float(_np.abs(l.reshape(p.shape) - p).mean())
            self.num_inst += 1


class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = label.asnumpy(), pred.asnumpy()
            self.sum_metric += float(((l.reshape(p.shape) - p) ** 2).mean())
            self.num_inst += 1


class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l, p = label.asnumpy(), pred.asnumpy()
            self.sum_metric += float(_np.sqrt(((l.reshape(p.shape) - p) ** 2).mean()))
            self.num_inst += 1


class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            pred_np = pred.asnumpy()
            prob = pred_np[_np.arange(label_np.shape[0]), label_np]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label_np.shape[0]


class Perplexity(EvalMetric):
    """exp(mean NLL) for language models; ``ignore_label`` entries
    (padding from bucketing) are excluded (parity: mx.metric.Perplexity
    as used by example/rnn training scripts)."""

    def __init__(self, ignore_label=None, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss, num = 0.0, 0
        for label, pred in zip(labels, preds):
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            pred_np = pred.asnumpy()
            if self.axis not in (-1, pred_np.ndim - 1):
                pred_np = _np.moveaxis(pred_np, self.axis, -1)
            pred_np = pred_np.reshape(label_np.shape[0], -1)
            prob = pred_np[_np.arange(label_np.shape[0]),
                           _np.clip(label_np, 0, pred_np.shape[1] - 1)]
            mask = _np.ones_like(prob, dtype=bool)
            if self.ignore_label is not None:
                mask = label_np != self.ignore_label
            loss += float(-_np.log(_np.maximum(prob[mask], 1e-10)).sum())
            num += int(mask.sum())
        self.sum_metric += loss
        self.num_inst += num

    def _value(self, s, n):
        return float(_np.exp(s / n)) if n else float("nan")


class Torch(EvalMetric):
    """Parity stub: metric.py Torch (average of preds)."""

    def __init__(self, name="torch"):
        super().__init__(name)

    def update(self, labels, preds):
        for pred in preds:
            self.sum_metric += float(pred.asnumpy().mean())
        self.num_inst += 1


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False):
        super().__init__(name or getattr(feval, "__name__", "custom"))
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            res = self._feval(label.asnumpy(), pred.asnumpy())
            if isinstance(res, tuple):
                s, n = res
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += res
                self.num_inst += 1


def np_metric(name=None, allow_extra_outputs=False):
    """Parity: mx.metric.np decorator."""

    def deco(feval):
        return CustomMetric(feval, name, allow_extra_outputs)

    return deco


def np(numpy_feval=None, name=None, allow_extra_outputs=False):
    """Parity: mx.metric.np — wrap a numpy function as an EvalMetric.

    Usable both ways the reference allows:
      mx.metric.np(CRPS)                      # direct wrap
      @mx.metric.np                            # bare decorator
      @mx.metric.np(name="crps")               # configured decorator
    """
    if callable(numpy_feval):
        return CustomMetric(numpy_feval, name, allow_extra_outputs)
    return np_metric(name=name, allow_extra_outputs=allow_extra_outputs)

_METRICS = {
    "acc": Accuracy,
    "accuracy": Accuracy,
    "f1": F1,
    "mae": MAE,
    "mse": MSE,
    "rmse": RMSE,
    "ce": CrossEntropy,
    "cross-entropy": CrossEntropy,
    "torch": Torch,
    "perplexity": Perplexity,
}


def create(metric, **kwargs):
    """Parity: mx.metric.create."""
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        comp = CompositeEvalMetric()
        for m in metric:
            comp.add(create(m, **kwargs))
        return comp
    if isinstance(metric, str):
        if metric.startswith("top_k_accuracy"):
            parts = metric.split("_")
            return TopKAccuracy(top_k=int(parts[-1])) if parts[-1].isdigit() else TopKAccuracy()
        if metric.lower() in _METRICS:
            return _METRICS[metric.lower()](**kwargs)
    raise MXNetError(f"unknown metric {metric}")
