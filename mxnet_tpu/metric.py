"""Evaluation metrics (parity: python/mxnet/metric.py).

EvalMetric registry: Accuracy, TopKAccuracy, F1, MAE/MSE/RMSE,
CrossEntropy, Perplexity, Loss, CustomMetric (+np wrapper),
CompositeEvalMetric.

Two accumulation paths:

- **fused (default)** — each built-in metric contributes a jitted
  ``(sum, num) += f(label, pred)`` accumulator whose running totals live
  as DEVICE scalars: ``update()`` only *enqueues* one async dispatch, and
  the device→host sync happens when a reader (``get()`` /
  ``get_name_value()`` / ``reset_local()``) actually needs the values.
  This is what keeps the training hot loop free of per-batch ``asnumpy``
  stalls (the reference syncs every batch: SURVEY.md §3.1 update_metric).
  ``MXTPU_FUSED_METRICS=0`` opts out.
- **eager** — the reference's host-numpy path, used automatically for
  ``CustomMetric``/``mx.metric.np`` callbacks, F1/Torch, multi-output
  (``num=``) metrics, and non-array inputs.

Both paths share the accumulators, so fused and eager updates can
interleave freely (a fused window is folded in before any eager read).
"""
from __future__ import annotations

import os

import numpy as _np

from . import telemetry as _tm
from .base import MXNetError, parse_bool

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_FUSED = _tm.counter(
    "metric_fused_update_total",
    "metric updates accumulated device-side (no host sync)",
    labels=("metric",))
_TM_SYNC = _tm.counter(
    "metric_host_sync_total",
    "device->host metric syncs: fused-path drains (one per value read "
    "with pending updates) + eager-path asnumpy updates (one per "
    "label/pred pair)", labels=("metric",))


def fused_metrics_enabled() -> bool:
    """MXTPU_FUSED_METRICS gate (default on)."""
    return parse_bool(os.environ.get("MXTPU_FUSED_METRICS", "1"))


def _device_raw(x):
    """The raw jax array behind a metric input, WITHOUT a host sync —
    or None when the input has no device representation (plain numpy /
    lists take the eager path)."""
    import jax

    read = getattr(x, "_read", None)  # NDArray (views resolve lazily)
    if read is not None:
        return read()
    if isinstance(x, jax.Array):
        return x
    return None


def check_label_shapes(labels, preds, shape=False):
    if len(labels) != len(preds):
        raise MXNetError(f"label/pred count mismatch: {len(labels)} vs {len(preds)}")


class EvalMetric:
    """Base metric with a local/global accumulator split.

    Subclasses only ever touch ``sum_metric``/``num_inst`` (the *local*
    window).  ``reset_local()`` folds the window into carried totals and
    clears it — so interval reporters (Speedometer auto_reset) can print
    per-window values while ``get_global_name_value()`` still returns the
    true since-``reset()`` aggregate for epoch-end logs.  (The v0.9.4
    reference lacks this split and its epoch log after an auto_reset
    Speedometer covers only the tail window; later MXNet added
    reset_local/get_global, which is the behavior reproduced here.)

    Fused accumulation: a subclass that defines ``_fused_delta(label,
    pred) -> (sum_delta, num_delta)`` (pure jnp, traceable) gets the
    device-resident path for free — its ``update`` calls
    ``_fused_accumulate`` per (label, pred) pair and only falls through
    to its eager numpy body when the fused path is unavailable.
    """

    # subclasses override with a jnp-traceable method; None = eager-only
    _fused_delta = None

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self._fused_jit = None
        # bumped on every fused enqueue: together with the (host-cheap)
        # accumulator values it forms update_stamp(), the sync-free
        # "anything new since I last looked?" token Speedometer uses
        self._version = 0
        self.reset()

    def reset(self):
        # pending device window is DISCARDED, not synced — reset means
        # "forget everything", same as zeroing the host accumulators
        self._dev_sum = None
        self._dev_num = None
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
            self._carried_num = 0
            self._carried_sum = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num
            self._carried_num = [0] * self.num
            self._carried_sum = [0.0] * self.num

    def reset_local(self):
        """Fold the current window into the global totals and clear it."""
        self._drain()
        if self.num is None:
            self._carried_num += self.num_inst
            self._carried_sum += self.sum_metric
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            for i in range(self.num):
                self._carried_num[i] += self.num_inst[i]
                self._carried_sum[i] += self.sum_metric[i]
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    # ------------------------------------------------------------- fused path
    def _fused_fn(self):
        if self._fused_delta is None:
            return None
        if self._fused_jit is None:
            import jax

            delta = self._fused_delta

            def acc(s, n, label, pred):
                ds, dn = delta(label, pred)
                return s + ds, n + dn

            self._fused_jit = jax.jit(acc)
        return self._fused_jit

    def _fused_accumulate(self, label, pred) -> bool:
        """Try to fold one (label, pred) pair into the device window.

        Returns False (caller runs its eager numpy body) when fused
        metrics are disabled, the metric has no fused kernel or uses
        multi-output accumulators, or the inputs are not device arrays.
        On success the accumulate is ONE async dispatch — no host sync.
        """
        if self.num is not None or not fused_metrics_enabled():
            return False
        fn = self._fused_fn()
        if fn is None:
            return False
        raw_p = _device_raw(pred)
        if raw_p is None:
            return False
        if label is None:
            raw_l = 0.0  # label-free metrics (Loss) ignore it
        else:
            raw_l = _device_raw(label)
            if raw_l is None:
                return False
        import jax
        import jax.numpy as jnp

        # sharded preds (data-parallel executor group): every jit input
        # must live on the same device set, so the accumulators (and a
        # host-resident label) are replicated over the pred's mesh
        rep = None
        sh = getattr(raw_p, "sharding", None)
        if sh is not None and len(sh.device_set) > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            if not isinstance(sh, NamedSharding):
                return False  # unknown multi-device layout: eager path

            def _replicate(val, _rep):
                # a mesh spanning other processes cannot device_put a
                # committed local array (non-addressable devices): each
                # process contributes its addressable shards of the
                # replicated value instead (docs/multihost.md)
                import numpy as _np

                me = jax.process_index()
                if all(d.process_index == me for d in _rep.device_set):
                    return jax.device_put(val, _rep)
                host = _np.asarray(val)
                return jax.make_array_from_callback(
                    host.shape, _rep, lambda idx, _h=host: _h[idx])

            rep = NamedSharding(sh.mesh, PartitionSpec())
            if label is None:
                raw_l = _replicate(jnp.float32(0.0), rep)
            elif len(getattr(raw_l, "sharding",
                             sh).device_set) != len(sh.device_set):
                raw_l = _replicate(raw_l, rep)
        if (rep is None and self._dev_sum is not None
                and len(self._dev_sum.sharding.device_set) > 1):
            # mesh -> single-device transition (metric reused across
            # modules): fold the sharded window out rather than mixing
            self._drain()
        if self._dev_sum is None:
            self._dev_sum = jnp.zeros((), jnp.float32)
            self._dev_num = jnp.zeros((), jnp.float32)
        if rep is not None and len(
                self._dev_sum.sharding.device_set) != len(sh.device_set):
            self._dev_sum = _replicate(self._dev_sum, rep)
            self._dev_num = _replicate(self._dev_num, rep)
        self._dev_sum, self._dev_num = fn(self._dev_sum, self._dev_num,
                                          raw_l, raw_p)
        self._version += 1
        if _tm.enabled():
            _TM_FUSED.inc(metric=self.name)
        return True

    def _drain(self):
        """Fold the device window into the host accumulators.  This is
        the ONLY device→host sync point of the fused path."""
        if self._dev_sum is None:
            return
        s, n = self._dev_sum, self._dev_num
        self._dev_sum = None
        self._dev_num = None
        self.sum_metric += float(s)
        n = float(n)
        # eager counts are ints (len(label)); keep that type when exact
        self.num_inst += int(n) if n.is_integer() else n
        if _tm.enabled():
            _TM_SYNC.inc(metric=self.name)

    def _eager_sync(self):
        """Record one eager-path device->host sync (an update pair that
        went through asnumpy) in the same family the fused drains use —
        the fused-vs-eager sync count is the bench's pipeline story."""
        if _tm.enabled():
            _TM_SYNC.inc(metric=self.name)

    def update_stamp(self):
        """Cheap sync-free token that changes whenever this metric has
        received updates (Speedometer's "values needed" guard): fused
        enqueues bump ``_version``; eager updates move the host
        accumulators directly."""

        def _t(v):
            return tuple(v) if isinstance(v, list) else v

        return (self._version, _t(self.num_inst), _t(self.sum_metric))

    def update(self, labels, preds):
        raise NotImplementedError

    def _value(self, s, n):
        """Accumulators -> reported value; metrics with a non-mean readout
        (e.g. Perplexity's exp) override THIS so local and global views
        stay consistent."""
        return s / n if n else float("nan")

    def get(self):
        self._drain()
        if self.num is None:
            return (self.name, self._value(self.sum_metric, self.num_inst))
        names = [f"{self.name}_{i}" for i in range(self.num)]
        values = [self._value(s, n)
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return (names, values)

    def get_global(self):
        self._drain()
        if self.num is None:
            return (self.name, self._value(self._carried_sum + self.sum_metric,
                                           self._carried_num + self.num_inst))
        names = [f"{self.name}_{i}" for i in range(self.num)]
        values = [
            self._value(cs + s, cn + n)
            for cs, s, cn, n in zip(self._carried_sum, self.sum_metric,
                                    self._carried_num, self.num_inst)
        ]
        return (names, values)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))

    def get_global_name_value(self):
        name, value = self.get_global()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite"):
        super().__init__(name)
        self.metrics = metrics or []

    def add(self, metric):
        self.metrics.append(metric)

    def reset(self):
        self._dev_sum = None
        self._dev_num = None
        for m in getattr(self, "metrics", []):
            m.reset()

    def reset_local(self):
        for m in self.metrics:
            m.reset_local()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def update_stamp(self):
        return tuple(m.update_stamp() for m in self.metrics)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return (names, values)

    def get_global(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get_global()
            names.append(n)
            values.append(v)
        return (names, values)


class Accuracy(EvalMetric):
    """Parity: metric.py Accuracy — argmax over axis 1 when needed.

    ``ignore_label`` drops masked entries (padding frames in bucketed
    sequence training) from both numerator and denominator, pairing with
    SoftmaxOutput(use_ignore=True)."""

    def __init__(self, ignore_label=None):
        super().__init__("accuracy")
        self.ignore_label = ignore_label

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        label = label.astype(jnp.int32)
        if pred.ndim > 1 and pred.shape != label.shape:
            pred = pred.argmax(axis=1)
        pred = pred.astype(jnp.int32).reshape(-1)
        label = label.reshape(-1)
        if self.ignore_label is not None:
            keep = label != self.ignore_label
            return (((pred == label) & keep).sum().astype(jnp.float32),
                    keep.sum().astype(jnp.float32))
        return ((pred == label).sum().astype(jnp.float32),
                jnp.float32(label.size))

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if self._fused_accumulate(label, pred):
                continue
            self._eager_sync()
            pred_np = pred.asnumpy()
            label_np = label.asnumpy().astype(_np.int32)
            if pred_np.ndim > 1 and pred_np.shape != label_np.shape:
                pred_np = pred_np.argmax(axis=1)
            pred_np = pred_np.astype(_np.int32).reshape(-1)
            label_np = label_np.reshape(-1)
            if self.ignore_label is not None:
                keep = label_np != self.ignore_label
                pred_np, label_np = pred_np[keep], label_np[keep]
            self.sum_metric += float((pred_np == label_np).sum())
            self.num_inst += len(label_np)


class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1):
        super().__init__(f"top_k_accuracy_{top_k}")
        self.top_k = top_k

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        label = label.astype(jnp.int32).reshape(-1)
        argsorted = jnp.argsort(-pred, axis=1)[:, : self.top_k]
        hits = (argsorted == label[:, None]).any(axis=1).sum()
        return hits.astype(jnp.float32), jnp.float32(label.size)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            if self._fused_accumulate(label, pred):
                continue
            self._eager_sync()
            pred_np = pred.asnumpy()
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            argsorted = _np.argsort(-pred_np, axis=1)[:, : self.top_k]
            self.sum_metric += float((argsorted == label_np[:, None]).any(axis=1).sum())
            self.num_inst += len(label_np)


class F1(EvalMetric):
    """Binary F1 (parity: metric.py F1).  Eager-only: the per-batch F1
    readout is not a (sum, num) fold."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred_np = pred.asnumpy()
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            if pred_np.ndim > 1:
                pred_np = pred_np.argmax(axis=1)
            pred_np = pred_np.astype(_np.int32).reshape(-1)
            tp = float(((pred_np == 1) & (label_np == 1)).sum())
            fp = float(((pred_np == 1) & (label_np == 0)).sum())
            fn = float(((pred_np == 0) & (label_np == 1)).sum())
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            f1 = 2 * precision * recall / (precision + recall) if precision + recall > 0 else 0.0
            self.sum_metric += f1
            self.num_inst += 1


class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        err = jnp.abs(label.reshape(pred.shape) - pred).mean()
        return err.astype(jnp.float32), jnp.float32(1.0)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            if self._fused_accumulate(label, pred):
                continue
            self._eager_sync()
            l, p = label.asnumpy(), pred.asnumpy()
            self.sum_metric += float(_np.abs(l.reshape(p.shape) - p).mean())
            self.num_inst += 1


class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        err = ((label.reshape(pred.shape) - pred) ** 2).mean()
        return err.astype(jnp.float32), jnp.float32(1.0)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            if self._fused_accumulate(label, pred):
                continue
            self._eager_sync()
            l, p = label.asnumpy(), pred.asnumpy()
            self.sum_metric += float(((l.reshape(p.shape) - p) ** 2).mean())
            self.num_inst += 1


class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        err = jnp.sqrt(((label.reshape(pred.shape) - pred) ** 2).mean())
        return err.astype(jnp.float32), jnp.float32(1.0)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            if self._fused_accumulate(label, pred):
                continue
            self._eager_sync()
            l, p = label.asnumpy(), pred.asnumpy()
            self.sum_metric += float(_np.sqrt(((l.reshape(p.shape) - p) ** 2).mean()))
            self.num_inst += 1


class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        label = label.astype(jnp.int32).reshape(-1)
        prob = pred[jnp.arange(label.shape[0]), label]
        return ((-jnp.log(prob + self.eps)).sum().astype(jnp.float32),
                jnp.float32(label.shape[0]))

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            if self._fused_accumulate(label, pred):
                continue
            self._eager_sync()
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            pred_np = pred.asnumpy()
            prob = pred_np[_np.arange(label_np.shape[0]), label_np]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label_np.shape[0]


class Perplexity(EvalMetric):
    """exp(mean NLL) for language models; ``ignore_label`` entries
    (padding from bucketing) are excluded (parity: mx.metric.Perplexity
    as used by example/rnn training scripts)."""

    def __init__(self, ignore_label=None, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        label = label.astype(jnp.int32).reshape(-1)
        if self.axis not in (-1, pred.ndim - 1):
            pred = jnp.moveaxis(pred, self.axis, -1)
        pred = pred.reshape(label.shape[0], -1)
        prob = pred[jnp.arange(label.shape[0]),
                    jnp.clip(label, 0, pred.shape[1] - 1)]
        nll = -jnp.log(jnp.maximum(prob, 1e-10))
        if self.ignore_label is not None:
            mask = label != self.ignore_label
            return ((nll * mask).sum().astype(jnp.float32),
                    mask.sum().astype(jnp.float32))
        return nll.sum().astype(jnp.float32), jnp.float32(label.shape[0])

    def update(self, labels, preds):
        fused_all = True
        loss, num = 0.0, 0
        for label, pred in zip(labels, preds):
            if self._fused_accumulate(label, pred):
                continue
            self._eager_sync()
            fused_all = False
            label_np = label.asnumpy().astype(_np.int32).reshape(-1)
            pred_np = pred.asnumpy()
            if self.axis not in (-1, pred_np.ndim - 1):
                pred_np = _np.moveaxis(pred_np, self.axis, -1)
            pred_np = pred_np.reshape(label_np.shape[0], -1)
            prob = pred_np[_np.arange(label_np.shape[0]),
                           _np.clip(label_np, 0, pred_np.shape[1] - 1)]
            mask = _np.ones_like(prob, dtype=bool)
            if self.ignore_label is not None:
                mask = label_np != self.ignore_label
            loss += float(-_np.log(_np.maximum(prob[mask], 1e-10)).sum())
            num += int(mask.sum())
        if not fused_all:
            self.sum_metric += loss
            self.num_inst += num

    def _value(self, s, n):
        return float(_np.exp(s / n)) if n else float("nan")


class Loss(EvalMetric):
    """Mean of the raw loss outputs (parity: mx.metric.Loss — "dummy"
    metric for printing a MakeLoss/LinearRegressionOutput head).  Labels
    are ignored."""

    def __init__(self, name="loss"):
        super().__init__(name)

    def _fused_delta(self, label, pred):
        import jax.numpy as jnp

        return (pred.sum().astype(jnp.float32), jnp.float32(pred.size))

    def update(self, labels, preds):
        for pred in preds:
            if self._fused_accumulate(None, pred):
                continue
            self._eager_sync()
            pred_np = pred.asnumpy()
            self.sum_metric += float(pred_np.sum())
            self.num_inst += pred_np.size


class Torch(EvalMetric):
    """Parity stub: metric.py Torch (average of preds)."""

    def __init__(self, name="torch"):
        super().__init__(name)

    def update(self, labels, preds):
        for pred in preds:
            self.sum_metric += float(pred.asnumpy().mean())
        self.num_inst += 1


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False):
        super().__init__(name or getattr(feval, "__name__", "custom"))
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            res = self._feval(label.asnumpy(), pred.asnumpy())
            if isinstance(res, tuple):
                s, n = res
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += res
                self.num_inst += 1


def np_metric(name=None, allow_extra_outputs=False):
    """Parity: mx.metric.np decorator."""

    def deco(feval):
        return CustomMetric(feval, name, allow_extra_outputs)

    return deco


def np(numpy_feval=None, name=None, allow_extra_outputs=False):
    """Parity: mx.metric.np — wrap a numpy function as an EvalMetric.

    Usable both ways the reference allows:
      mx.metric.np(CRPS)                      # direct wrap
      @mx.metric.np                            # bare decorator
      @mx.metric.np(name="crps")               # configured decorator
    """
    if callable(numpy_feval):
        return CustomMetric(numpy_feval, name, allow_extra_outputs)
    return np_metric(name=name, allow_extra_outputs=allow_extra_outputs)

_METRICS = {
    "acc": Accuracy,
    "accuracy": Accuracy,
    "f1": F1,
    "mae": MAE,
    "mse": MSE,
    "rmse": RMSE,
    "ce": CrossEntropy,
    "cross-entropy": CrossEntropy,
    "torch": Torch,
    "loss": Loss,
    "perplexity": Perplexity,
}


def create(metric, **kwargs):
    """Parity: mx.metric.create."""
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        comp = CompositeEvalMetric()
        for m in metric:
            comp.add(create(m, **kwargs))
        return comp
    if isinstance(metric, str):
        if metric.startswith("top_k_accuracy"):
            parts = metric.split("_")
            return TopKAccuracy(top_k=int(parts[-1])) if parts[-1].isdigit() else TopKAccuracy()
        if metric.lower() in _METRICS:
            return _METRICS[metric.lower()](**kwargs)
    raise MXNetError(f"unknown metric {metric}")
