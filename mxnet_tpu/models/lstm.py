"""Unrolled LSTM language model (parity: example/rnn/lstm.py — the
lstm_bucketing/PTB workload; also the model-parallel variant
example/model-parallel-lstm/lstm.py, whose per-layer ctx_group annotations
map to mesh sharding groups here).
"""
from collections import namedtuple

from .. import symbol as sym

LSTMState = namedtuple("LSTMState", ["c", "h"])
LSTMParam = namedtuple("LSTMParam", ["i2h_weight", "i2h_bias", "h2h_weight", "h2h_bias"])


def lstm(num_hidden, indata, prev_state, param, seqidx, layeridx, dropout=0.0):
    """One LSTM step (parity: example/rnn/lstm.py lstm())."""
    if dropout > 0.0:
        indata = sym.Dropout(indata, p=dropout)
    i2h = sym.FullyConnected(indata, weight=param.i2h_weight, bias=param.i2h_bias,
                             num_hidden=num_hidden * 4,
                             name=f"t{seqidx}_l{layeridx}_i2h")
    h2h = sym.FullyConnected(prev_state.h, weight=param.h2h_weight,
                             bias=param.h2h_bias, num_hidden=num_hidden * 4,
                             name=f"t{seqidx}_l{layeridx}_h2h")
    gates = i2h + h2h
    slice_gates = sym.SliceChannel(gates, num_outputs=4,
                                   name=f"t{seqidx}_l{layeridx}_slice")
    in_gate = sym.Activation(slice_gates[0], act_type="sigmoid")
    in_transform = sym.Activation(slice_gates[1], act_type="tanh")
    forget_gate = sym.Activation(slice_gates[2], act_type="sigmoid")
    out_gate = sym.Activation(slice_gates[3], act_type="sigmoid")
    next_c = (forget_gate * prev_state.c) + (in_gate * in_transform)
    next_h = out_gate * sym.Activation(next_c, act_type="tanh")
    return LSTMState(c=next_c, h=next_h)


def lstm_unroll(num_lstm_layer, seq_len, input_size, num_hidden, num_embed,
                num_label, dropout=0.0, ignore_label=None):
    """Parity: example/rnn/lstm.py lstm_unroll — the bucketing sym_gen body.

    ``ignore_label`` masks that label id out of the loss (use_ignore
    SoftmaxOutput) — required for exact gradients under compile-bucket
    padding (BucketingModule(compile_buckets=...))."""
    embed_weight = sym.Variable("embed_weight")
    cls_weight = sym.Variable("cls_weight")
    cls_bias = sym.Variable("cls_bias")
    param_cells = []
    last_states = []
    for i in range(num_lstm_layer):
        param_cells.append(LSTMParam(
            i2h_weight=sym.Variable(f"l{i}_i2h_weight"),
            i2h_bias=sym.Variable(f"l{i}_i2h_bias"),
            h2h_weight=sym.Variable(f"l{i}_h2h_weight"),
            h2h_bias=sym.Variable(f"l{i}_h2h_bias")))
        last_states.append(LSTMState(
            c=sym.Variable(f"l{i}_init_c"), h=sym.Variable(f"l{i}_init_h")))

    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, weight=embed_weight, input_dim=input_size,
                          output_dim=num_embed, name="embed")
    wordvec = sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                               squeeze_axis=True)

    hidden_all = []
    for seqidx in range(seq_len):
        hidden = wordvec[seqidx]
        for i in range(num_lstm_layer):
            next_state = lstm(num_hidden, indata=hidden,
                              prev_state=last_states[i], param=param_cells[i],
                              seqidx=seqidx, layeridx=i,
                              dropout=dropout if i > 0 else 0.0)
            hidden = next_state.h
            last_states[i] = next_state
        if dropout > 0.0:
            hidden = sym.Dropout(hidden, p=dropout)
        hidden_all.append(hidden)

    hidden_concat = sym.Concat(*hidden_all, dim=0)
    pred = sym.FullyConnected(hidden_concat, num_hidden=num_label,
                              weight=cls_weight, bias=cls_bias, name="pred")
    label_t = sym.transpose(label)
    label_flat = sym.Reshape(label_t, shape=(-1,))
    if ignore_label is not None:
        return sym.SoftmaxOutput(pred, label_flat, name="softmax",
                                 use_ignore=True, ignore_label=ignore_label)
    return sym.SoftmaxOutput(pred, label_flat, name="softmax")


def get_symbol(num_classes=10000, seq_len=32, num_hidden=200, num_embed=200,
               num_lstm_layer=2, dropout=0.2, **kwargs):
    return lstm_unroll(num_lstm_layer, seq_len, num_classes, num_hidden,
                       num_embed, num_classes, dropout)
