"""SSD-VGG16-300 detector (parity: example/ssd/symbol/symbol_vgg16_ssd_300.py
+ example/ssd/symbol/common.py multi_layer_feature/multibox_layer).

get_symbol_train: training graph ending in the multibox target + losses
(SoftmaxOutput over matched classes, SmoothL1 on localization offsets).
get_symbol: deploy graph ending in MultiBoxDetection NMS output.
"""
from .. import symbol as sym

# per-scale anchor config (reference symbol_vgg16_ssd_300.py:12-22)
_SIZES = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
          (0.71, 0.79), (0.88, 0.961)]
_RATIOS = [(1, 2, 0.5), (1, 2, 0.5, 3, 1.0 / 3), (1, 2, 0.5, 3, 1.0 / 3),
           (1, 2, 0.5, 3, 1.0 / 3), (1, 2, 0.5), (1, 2, 0.5)]
_NORMALIZATION = [20, -1, -1, -1, -1, -1]


def _conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1),
              stride=(1, 1), dilate=(1, 1)):
    conv = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           pad=pad, stride=stride, dilate=dilate, name=name)
    return sym.Activation(conv, act_type="relu", name=f"relu_{name}")


def vgg16_base(data):
    """VGG16 through conv5_3 with the SSD modifications: pool5 3x3/1,
    fc6 as dilated conv, fc7 as 1x1 conv (reference vgg16_reduced)."""
    x = _conv_act(data, "conv1_1", 64)
    x = _conv_act(x, "conv1_2", 64)
    x = sym.Pooling(x, pool_type="max", kernel=(2, 2), stride=(2, 2),
                    name="pool1")
    x = _conv_act(x, "conv2_1", 128)
    x = _conv_act(x, "conv2_2", 128)
    x = sym.Pooling(x, pool_type="max", kernel=(2, 2), stride=(2, 2),
                    name="pool2")
    x = _conv_act(x, "conv3_1", 256)
    x = _conv_act(x, "conv3_2", 256)
    x = _conv_act(x, "conv3_3", 256)
    # "full" (ceil) convention keeps conv4_3 at 38x38 for 300-input
    # (reference symbol_vgg16_reduced.py pool3 pooling_convention="full")
    x = sym.Pooling(x, pool_type="max", kernel=(2, 2), stride=(2, 2),
                    pooling_convention="full", name="pool3")
    x = _conv_act(x, "conv4_1", 512)
    x = _conv_act(x, "conv4_2", 512)
    conv4_3 = _conv_act(x, "conv4_3", 512)
    x = sym.Pooling(conv4_3, pool_type="max", kernel=(2, 2), stride=(2, 2),
                    name="pool4")
    x = _conv_act(x, "conv5_1", 512)
    x = _conv_act(x, "conv5_2", 512)
    x = _conv_act(x, "conv5_3", 512)
    x = sym.Pooling(x, pool_type="max", kernel=(3, 3), stride=(1, 1),
                    pad=(1, 1), name="pool5")
    fc6 = _conv_act(x, "fc6", 1024, kernel=(3, 3), pad=(6, 6), dilate=(6, 6))
    fc7 = _conv_act(fc6, "fc7", 1024, kernel=(1, 1), pad=(0, 0))
    return conv4_3, fc7


def _extra_layers(fc7):
    """conv6..conv9 downsampling pyramid (reference common.py)."""
    layers = []
    x = _conv_act(fc7, "conv6_1", 256, kernel=(1, 1), pad=(0, 0))
    x = _conv_act(x, "conv6_2", 512, kernel=(3, 3), pad=(1, 1),
                  stride=(2, 2))
    layers.append(x)
    y = _conv_act(x, "conv7_1", 128, kernel=(1, 1), pad=(0, 0))
    y = _conv_act(y, "conv7_2", 256, kernel=(3, 3), pad=(1, 1),
                  stride=(2, 2))
    layers.append(y)
    z = _conv_act(y, "conv8_1", 128, kernel=(1, 1), pad=(0, 0))
    z = _conv_act(z, "conv8_2", 256, kernel=(3, 3), pad=(0, 0))
    layers.append(z)
    w = _conv_act(z, "conv9_1", 128, kernel=(1, 1), pad=(0, 0))
    w = _conv_act(w, "conv9_2", 256, kernel=(3, 3), pad=(0, 0))
    layers.append(w)
    return layers


def multibox_layer(from_layers, num_classes, sizes, ratios, normalization):
    """Per-scale loc/cls heads + priors (parity: common.py multibox_layer).
    num_classes here EXCLUDES background; heads predict num_classes+1."""
    loc_layers, cls_layers, anchor_layers = [], [], []
    for k, from_layer in enumerate(from_layers):
        name = from_layer.name
        if normalization[k] > 0:
            from_layer = sym.L2Normalization(from_layer, mode="channel",
                                             name=f"{name}_norm")
            scale = sym.Variable(f"{name}_scale", shape=(1, 512, 1, 1),
                                 init='["constant", {"value": 20.0}]')
            from_layer = sym.broadcast_mul(from_layer, scale)
        num_anchors = len(sizes[k]) + len(ratios[k]) - 1
        # location offsets: 4 per anchor
        loc = sym.Convolution(from_layer, num_filter=num_anchors * 4,
                              kernel=(3, 3), pad=(1, 1),
                              name=f"{name}_loc_pred_conv")
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc = sym.Flatten(loc)
        loc_layers.append(loc)
        # class predictions: (num_classes + 1) per anchor
        cls = sym.Convolution(from_layer,
                              num_filter=num_anchors * (num_classes + 1),
                              kernel=(3, 3), pad=(1, 1),
                              name=f"{name}_cls_pred_conv")
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls = sym.Flatten(cls)
        cls_layers.append(cls)
        anchors = sym.MultiBoxPrior(from_layer, sizes=sizes[k],
                                    ratios=ratios[k], clip=False,
                                    name=f"{name}_anchors")
        anchor_layers.append(sym.Flatten(anchors))

    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_layers, dim=1)
    cls_preds = sym.Reshape(cls_preds, shape=(0, -1, num_classes + 1))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1),
                              name="multibox_cls_pred")
    anchor_boxes = sym.Concat(*anchor_layers, dim=1)
    anchor_boxes = sym.Reshape(anchor_boxes, shape=(0, -1, 4),
                               name="multibox_anchors")
    return loc_preds, cls_preds, anchor_boxes


def tiny_base(data):
    """4-conv trunk for from-scratch training (the reference always
    fine-tunes a pretrained VGG; a 13-conv VGG from random init cannot
    learn in a short CPU run — this trunk can, and exercises the same
    two-scale multibox head wiring)."""
    net = data
    for i, nf in enumerate((16, 32)):
        net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                              num_filter=nf, name=f"tconv{i + 1}")
        net = sym.Activation(net, act_type="relu")
        net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")
    c3 = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=32,
                         name="tconv3")
    c3 = sym.Activation(c3, act_type="relu", name="tiny_scale1")
    c4 = sym.Pooling(c3, kernel=(2, 2), stride=(2, 2), pool_type="max")
    c4 = sym.Convolution(c4, kernel=(3, 3), pad=(1, 1), num_filter=64,
                         name="tconv4")
    c4 = sym.Activation(c4, act_type="relu", name="tiny_scale2")
    return c3, c4


_TINY_SIZES = [(0.2, 0.272), (0.4, 0.5, 0.65)]
_TINY_RATIOS = [(1, 2, 0.5), (1, 2, 0.5)]
_TINY_NORMALIZATION = [-1, -1]


def _build(num_classes, backbone="vgg16"):
    data = sym.Variable("data")
    if backbone == "tiny":
        s1, s2 = tiny_base(data)
        return multibox_layer([s1, s2], num_classes, _TINY_SIZES,
                              _TINY_RATIOS, _TINY_NORMALIZATION)
    conv4_3, fc7 = vgg16_base(data)
    extras = _extra_layers(fc7)
    from_layers = [conv4_3, fc7] + extras
    return multibox_layer(from_layers, num_classes, _SIZES, _RATIOS,
                          _NORMALIZATION)


def get_symbol_train(num_classes=20, backbone="vgg16", **kwargs):
    """Training graph (parity: symbol_vgg16_ssd_300.py get_symbol_train):
    label is (N, M, 5) [cls, x1, y1, x2, y2] normalized, -1-padded."""
    label = sym.Variable("label")
    loc_preds, cls_preds, anchor_boxes = _build(num_classes, backbone)

    loc_target, loc_target_mask, cls_target = sym.MultiBoxTarget(
        anchor_boxes, label, cls_preds, overlap_threshold=0.5,
        ignore_label=-1, negative_mining_ratio=3,
        negative_mining_thresh=0.5, variances=(0.1, 0.1, 0.2, 0.2),
        name="multibox_target")
    cls_prob = sym.SoftmaxOutput(cls_preds, cls_target,
                                 ignore_label=-1, use_ignore=True,
                                 multi_output=True,
                                 normalization="valid", name="cls_prob")
    loc_diff = loc_preds - loc_target
    masked_loc_diff = loc_target_mask * loc_diff
    loc_loss_ = sym.smooth_l1(masked_loc_diff, scalar=1.0,
                              name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, grad_scale=1.0,
                            normalization="valid", name="loc_loss")
    # monitoring outputs (BlockGrad'd like the reference)
    cls_label = sym.BlockGrad(cls_target, name="cls_label")
    det = sym.MultiBoxDetection(cls_prob, loc_preds, anchor_boxes,
                                name="detection", nms_threshold=0.45,
                                force_suppress=False, variances=(0.1, 0.1,
                                                                 0.2, 0.2),
                                nms_topk=400)
    det = sym.BlockGrad(det, name="det_out")
    return sym.Group([cls_prob, loc_loss, cls_label, det])


def get_symbol(num_classes=20, nms_thresh=0.5, force_suppress=False,
               nms_topk=400, backbone="vgg16", **kwargs):
    """Deploy graph: softmax over classes + NMS detection output."""
    loc_preds, cls_preds, anchor_boxes = _build(num_classes, backbone)
    cls_prob = sym.SoftmaxActivation(cls_preds, mode="channel",
                                     name="cls_prob")
    return sym.MultiBoxDetection(cls_prob, loc_preds, anchor_boxes,
                                 name="detection", nms_threshold=nms_thresh,
                                 force_suppress=force_suppress,
                                 variances=(0.1, 0.1, 0.2, 0.2),
                                 nms_topk=nms_topk)
