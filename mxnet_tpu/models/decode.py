"""Incremental (KV-cache) decoding for the transformer LM
(beyond-reference: the reference has no autoregressive serving story —
its RNN demos re-run full windows per token.  This is the standard
O(T) decode: prefill once, then one position per step against cached
K/V, everything jitted with static shapes).

Works straight off a `models.transformer.transformer_lm` checkpoint:
the decoder reads the SAME arg_params the training symbol binds
(tok_embed/pos_embed/layer{i}_*/final_ln/lm_head), re-expressing the
forward functionally so each step is one XLA program with
`lax.dynamic_update_slice` into a (L, B, H, max_len, dh) cache.
`tests/test_decode.py` pins step-by-step equivalence against the
symbol graph's full forward.
"""
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _logsumexp(x):
    m = x.max(-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(-1, keepdims=True))


def _ln(x, g, b, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * g + b


def _fc(x, w, b=None):
    y = x @ w.T
    return y if b is None else y + b


class KVDecoder:
    """One instance per (checkpoint, batch, max_len) combination.

    state = (k_cache, v_cache, pos):
      k/v_cache (L, B, H, max_len, dh); pos int32 — tokens filled so far.
    """

    def __init__(self, arg_params, num_layers, num_heads, max_len,
                 dtype=jnp.float32, mesh=None, model_axis="model"):
        """``mesh``: shard serving over devices, Megatron-style — q/k/v
        and ffn_in weights column-parallel, proj and ffn_out
        row-parallel, the K/V cache split on its HEAD axis — so each
        device holds 1/tp of the weights and cache and GSPMD inserts
        the one all-reduce per block the row-parallel products need
        (the serving mirror of parallel/mesh.megatron_rules)."""
        to = lambda a: jnp.asarray(
            a.asnumpy() if hasattr(a, "asnumpy") else a, dtype)
        p = {k: to(v) for k, v in arg_params.items()}
        self.mesh = mesh
        self.model_axis = model_axis
        if mesh is not None:
            from ..parallel.mesh import megatron_rules, shard_params

            tp = mesh.shape[model_axis]
            if num_heads % tp:
                raise ValueError(
                    f"num_heads {num_heads} must divide by the "
                    f"{model_axis!r} mesh axis ({tp})")
            for k, v in p.items():
                if k.endswith("_ffn_in_weight") and v.shape[0] % tp:
                    raise ValueError(
                        f"{k}: d_ff {v.shape[0]} must divide by the "
                        f"{model_axis!r} mesh axis ({tp})")
            # the training layout, minus the vocab-sharded head/embed
            # (decode keeps logits replicated — the sampler reads them
            # on the host every step)
            rules = tuple(r for r in megatron_rules(model_axis)
                          if "lm_head" not in r.pattern
                          and "tok_embed" not in r.pattern)
            p = shard_params(mesh, p, rules)
        self.p = p
        self.L, self.H = num_layers, num_heads
        self.max_len = max_len
        self.d_model = p["tok_embed_weight"].shape[1]
        self.dh = self.d_model // num_heads
        self.vocab = p["lm_head_weight"].shape[0]
        if p["pos_embed"].shape[1] < max_len:
            raise ValueError(
                f"checkpoint pos table {p['pos_embed'].shape[1]} < "
                f"max_len {max_len}")
        self._step_jit = jax.jit(partial(self._forward_positions, n=1))
        self._reorder_jit = jax.jit(
            lambda kc, vc, idx: (kc[:, idx], vc[:, idx]))
        self._prefill_cache = {}
        self._scan_cache = {}

    def _cache_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        # (L, B, H, max_len, dh): split the head axis
        return NamedSharding(self.mesh, P(None, None, self.model_axis))

    # ---------------------------------------------------------------- core
    def _block_qkv(self, i, h2):
        p = self.p
        name = f"layer{i}"
        q = _fc(h2, p[f"{name}_q_weight"], p[f"{name}_q_bias"])
        k = _fc(h2, p[f"{name}_k_weight"], p[f"{name}_k_bias"])
        v = _fc(h2, p[f"{name}_v_weight"], p[f"{name}_v_bias"])
        return q, k, v

    def _forward_positions(self, kc, vc, pos, tokens, n):
        """Run ``n`` new positions (tokens (B, n)) against the cache.
        ``pos`` rides as a traced scalar; the HOST tracks the counter so
        no step ever fetches device state (on tunneled backends a
        per-step sync would dominate decode latency)."""
        p = self.p
        B = tokens.shape[0]
        H, dh, D = self.H, self.dh, self.d_model

        tok = jnp.take(p["tok_embed_weight"], tokens.astype(jnp.int32),
                       axis=0)                       # (B, n, D)
        posv = jax.lax.dynamic_slice(
            p["pos_embed"], (0, pos, 0), (1, n, D))
        h = tok + posv
        # positions 0..max_len-1 valid iff < pos+ their offset
        span = pos + jnp.arange(n)                   # (n,)
        mask = jnp.arange(self.max_len)[None, :] <= span[:, None]  # (n, S)
        for i in range(self.L):
            name = f"layer{i}"
            h2 = _ln(h, p[f"{name}_ln1_gamma"], p[f"{name}_ln1_beta"])
            q, k, v = self._block_qkv(i, h2)
            sh = lambda a: a.reshape(B, n, H, dh).transpose(0, 2, 1, 3)
            qh, kh, vh = sh(q), sh(k), sh(v)         # (B, H, n, dh)
            kc = jax.lax.dynamic_update_slice(
                kc, kh[None], (i, 0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, vh[None], (i, 0, 0, pos, 0))
            scores = jnp.einsum("bhnd,bhsd->bhns", qh, kc[i]) \
                / jnp.sqrt(jnp.asarray(dh, h.dtype))
            scores = jnp.where(mask[None, None], scores, NEG_INF)
            att = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhns,bhsd->bhnd", att, vc[i])
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, n, D)
            proj = _fc(ctx, p[f"{name}_proj_weight"],
                       p[f"{name}_proj_bias"])
            h = h + proj
            h2 = _ln(h, p[f"{name}_ln2_gamma"], p[f"{name}_ln2_beta"])
            f = _fc(h2, p[f"{name}_ffn_in_weight"],
                    p[f"{name}_ffn_in_bias"])
            f = jax.nn.gelu(f)
            f = _fc(f, p[f"{name}_ffn_out_weight"],
                    p[f"{name}_ffn_out_bias"])
            h = h + f
        h = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
        logits = _fc(h, p["lm_head_weight"], p["lm_head_bias"])
        return (kc, vc), logits                      # logits (B, n, V)

    # ----------------------------------------------------------------- API
    def init_state(self, batch):
        """state = (k_cache, v_cache, pos) — pos is a HOST int."""
        shape = (self.L, batch, self.H, self.max_len, self.dh)
        dtype = self.p["tok_embed_weight"].dtype
        if self.mesh is not None:
            # allocate SHARDED: each device holds 1/tp of the cache from
            # the start (a dense zeros + reshard would transiently put
            # the whole cache on one device)
            sh = self._cache_sharding()
            kc = jnp.zeros(shape, dtype, device=sh)
            vc = jnp.zeros(shape, dtype, device=sh)
        else:
            kc = jnp.zeros(shape, dtype)
            vc = jnp.zeros(shape, dtype)
        return (kc, vc, 0)

    def prefill(self, tokens):
        """tokens (B, T) -> (state, logits (B, T, V)); one compile per
        distinct prompt length."""
        tokens = jnp.asarray(tokens)
        B, T = tokens.shape
        if T > self.max_len:
            raise ValueError(f"prompt {T} > max_len {self.max_len}")
        if T not in self._prefill_cache:
            self._prefill_cache[T] = jax.jit(
                partial(self._forward_positions, n=T))
        kc, vc, pos = self.init_state(B)
        (kc, vc), logits = self._prefill_cache[T](kc, vc, pos, tokens)
        return (kc, vc, pos + T), logits

    def step(self, state, token):
        """token (B,) -> (state, logits (B, V)) — ONE fused XLA program
        per call, O(max_len) attention, no host-device sync."""
        kc, vc, pos = state
        if pos >= self.max_len:
            raise ValueError(
                f"cache full: {self.max_len} positions decoded (the "
                "checkpoint's positional table ends there)")
        (kc, vc), logits = self._step_jit(
            kc, vc, pos, jnp.asarray(token).reshape(-1, 1))
        return (kc, vc, pos + 1), logits[:, 0]

    def _check_generation_budget(self, prompt, n_tokens):
        """Shared generate()/generate_scan() prologue: normalized prompt
        plus the empty-result short-circuit (None when real work remains)."""
        prompt = np.asarray(prompt)
        total = prompt.shape[1] + n_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt+n_tokens = {total} exceeds max_len "
                f"{self.max_len} (the checkpoint's positional table)")
        empty = (np.zeros((prompt.shape[0], 0), np.int64)
                 if n_tokens <= 0 else None)
        return prompt, empty

    def generate(self, prompt, n_tokens, temperature=1.0, top_k=None,
                 rng=None):
        """Greedy/temperature sampling loop; returns (B, n_tokens)."""
        rng = rng or np.random.RandomState(0)
        prompt, empty = self._check_generation_budget(prompt, n_tokens)
        if empty is not None:
            return empty
        state, logits = self.prefill(prompt)
        last = logits[:, -1]
        out = []
        for i in range(n_tokens):
            lg = np.asarray(last, np.float32)
            if temperature <= 0:
                nxt = lg.argmax(-1)
            else:
                lg = lg / temperature
                if top_k:
                    kth = np.partition(lg, -top_k, axis=-1)[:, -top_k, None]
                    lg = np.where(lg < kth, -np.inf, lg)
                z = lg - lg.max(-1, keepdims=True)
                prob = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
                nxt = np.array([rng.choice(lg.shape[-1], p=p_)
                                for p_ in prob])
            out.append(nxt)
            if i + 1 < n_tokens:  # the last sampled token needs no step
                state, last = self.step(state, nxt)
        return np.stack(out, axis=1)

    def generate_scan(self, prompt, n_tokens, temperature=0.0,
                      top_k=None, seed=0, eos_id=None):
        """generate(), but the WHOLE autoregressive loop is one compiled
        lax.scan — one dispatch for n_tokens steps instead of one per
        token.  On high-latency links (the bench tunnel) per-token
        dispatch dominates decode throughput the same way it dominated
        small-batch training (trainer.step_multi); on a local host it
        simply removes n-1 dispatches.  Greedy when temperature<=0,
        otherwise categorical sampling (jax.random, seeded) with
        optional static top_k.  Token-for-token equal to generate() in
        greedy mode (pinned by tests/test_decode.py).

        With ``eos_id``, rows that emit it are eos-padded from then on
        (beam_search's convention) and the loop becomes a
        lax.while_loop that EXITS as soon as every row has finished —
        early stopping happens on device, still within the single
        dispatch."""
        prompt, empty = self._check_generation_budget(prompt, n_tokens)
        if empty is not None:
            return empty
        state, logits = self.prefill(prompt)
        kc, vc, pos = state
        key = (prompt.shape[0], n_tokens, float(temperature),
               top_k or 0, eos_id if eos_id is not None else -1)
        fn = self._scan_cache.get(key)
        if fn is None:
            greedy = temperature <= 0

            def pick(lg, k_):
                if top_k:
                    kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
                    lg = jnp.where(lg < kth, NEG_INF, lg)
                if greedy:
                    return jnp.argmax(lg, axis=-1)
                return jax.random.categorical(k_, lg / temperature)

            def step_once(kc, vc, pos, tok, k_):
                """ONE decode position + next-token pick — shared by the
                scan and while_loop bodies so they cannot diverge."""
                (kc, vc), lg = self._forward_positions(
                    kc, vc, pos, tok[:, None], n=1)
                k_, sub = jax.random.split(k_)
                return kc, vc, pick(lg[:, 0], sub), k_

            def loop(kc, vc, pos0, last_logits, rng_key):
                k0, krest = jax.random.split(rng_key)
                first = pick(last_logits, k0)

                def body(carry, i):
                    kc, vc, tok, k_ = carry
                    kc, vc, nxt, k_ = step_once(kc, vc, pos0 + i, tok, k_)
                    return (kc, vc, nxt, k_), nxt

                (kc, vc, _, _), rest = jax.lax.scan(
                    body, (kc, vc, first, krest),
                    jnp.arange(n_tokens - 1, dtype=jnp.int32))
                toks = jnp.concatenate(
                    [first[:, None], rest.transpose(1, 0)], axis=1)
                return kc, vc, toks

            def loop_eos(kc, vc, pos0, last_logits, rng_key):
                B = last_logits.shape[0]
                k0, krest = jax.random.split(rng_key)
                first = pick(last_logits, k0)
                done0 = first == eos_id
                buf = jnp.full((n_tokens, B), eos_id, jnp.int32)
                buf = buf.at[0].set(first.astype(jnp.int32))

                def cond(carry):
                    i, kc, vc, tok, k_, done, buf = carry
                    return jnp.logical_and(i < n_tokens - 1,
                                           jnp.logical_not(done.all()))

                def body(carry):
                    i, kc, vc, tok, k_, done, buf = carry
                    kc, vc, nxt, k_ = step_once(kc, vc, pos0 + i, tok, k_)
                    nxt = jnp.where(done, eos_id, nxt)  # freeze finished
                    done = jnp.logical_or(done, nxt == eos_id)
                    buf = buf.at[i + 1].set(nxt.astype(jnp.int32))
                    return (i + 1, kc, vc, nxt, k_, done, buf)

                (_, kc, vc, _, _, _, buf) = jax.lax.while_loop(
                    cond, body,
                    (jnp.int32(0), kc, vc, first, krest, done0, buf))
                return kc, vc, buf.transpose(1, 0)

            fn = jax.jit(loop if eos_id is None else loop_eos)
            self._scan_cache[key] = fn
        kc, vc, toks = fn(kc, vc, jnp.int32(pos),
                          logits[:, -1].astype(jnp.float32),
                          jax.random.PRNGKey(seed))
        return np.asarray(toks, np.int64)

    def beam_search(self, prompt, n_tokens, beam_size=4,
                    length_penalty=0.0, eos_id=None):
        """Beam decode: returns (tokens (B, beam, n_tokens),
        scores (B, beam)) sorted best-first per batch row.

        With ``eos_id`` set, beams that emit it stop accumulating score
        (further positions are eos-padded) and ``length_penalty``
        normalizes each beam's score by its OWN length^penalty — the
        standard way longer unfinished beams compete with short
        finished ones.  Without an eos, every beam has equal length and
        the penalty only rescales scores.

        The cache runs at batch B*beam from the start (prompt rows
        replicated); beam reordering is a jitted row-gather on the
        device cache, the bookkeeping (log-probs, back-pointers) stays
        host-side like the sampling loop."""
        prompt = np.asarray(prompt)
        B, T = prompt.shape
        if T + n_tokens > self.max_len:
            raise ValueError(
                f"prompt+n_tokens = {T + n_tokens} exceeds max_len "
                f"{self.max_len}")
        if beam_size > self.vocab:
            raise ValueError(
                f"beam_size {beam_size} > vocab {self.vocab}")
        if n_tokens <= 0:
            return (np.zeros((B, beam_size, 0), np.int64),
                    np.zeros((B, beam_size), np.float32))
        K = beam_size

        def topk(mat, k):
            part = np.argpartition(-mat, k - 1, axis=-1)[:, :k]
            vals = np.take_along_axis(mat, part, axis=-1)
            order = np.argsort(-vals, axis=-1)
            return np.take_along_axis(part, order, axis=-1)

        state, logits = self.prefill(np.repeat(prompt, K, axis=0))
        last = np.asarray(logits[:, -1], np.float32)     # (B*K, V)
        V = last.shape[-1]
        logp = last - _logsumexp(last)
        # first expansion: distinct top-K continuations per batch row
        first = logp.reshape(B, K, V)[:, 0]              # replicas identical
        top = topk(first, K)                             # (B, K)
        scores = np.take_along_axis(first, top, axis=-1)  # (B, K)
        seqs = top[:, :, None]                           # (B, K, 1)
        finished = (top == eos_id) if eos_id is not None \
            else np.zeros((B, K), bool)
        lengths = np.ones((B, K), np.int64)
        nxt = top.reshape(-1)
        for i in range(1, n_tokens):
            if finished.all():
                pad = np.full((B, K, n_tokens - i), eos_id, np.int64)
                seqs = np.concatenate([seqs, pad], axis=2)
                break
            state, lg = self.step(state, nxt)
            logp = np.asarray(lg, np.float32)
            logp = (logp - _logsumexp(logp)).reshape(B, K, V)
            cand = scores[:, :, None] + logp             # (B, K, V)
            if eos_id is not None:
                # a finished beam contributes exactly one candidate:
                # itself, eos-padded, score frozen
                cand[finished] = NEG_INF
                cand[finished, eos_id] = scores[finished]
            flat = cand.reshape(B, K * V)
            top = topk(flat, K)                          # (B, K)
            beam_idx, tok = top // V, top % V
            scores = np.take_along_axis(flat, top, axis=-1)
            seqs = np.concatenate(
                [np.take_along_axis(seqs, beam_idx[:, :, None], axis=1),
                 tok[:, :, None]], axis=2)
            parent_fin = np.take_along_axis(finished, beam_idx, axis=-1)
            lengths = np.take_along_axis(lengths, beam_idx, axis=-1) \
                + (~parent_fin)
            if eos_id is not None:
                finished = parent_fin | (tok == eos_id)
            nxt = tok.reshape(-1)
            if i + 1 < n_tokens and not finished.all():
                # follow the survivors on the device cache (skipped on
                # the last step — nothing consumes it)
                rows = (np.arange(B)[:, None] * K + beam_idx).reshape(-1)
                kc, vc, pos = state
                kc, vc = self._reorder_jit(kc, vc, jnp.asarray(rows))
                state = (kc, vc, pos)
        if length_penalty:
            scores = scores / (lengths.astype(np.float32)
                               ** length_penalty)
        order = np.argsort(-scores, axis=-1)
        return (np.take_along_axis(seqs, order[:, :, None], axis=1),
                np.take_along_axis(scores, order, axis=-1))
