"""Incremental (KV-cache) decoding for the transformer LM
(beyond-reference: the reference has no autoregressive serving story —
its RNN demos re-run full windows per token.  This is the standard
O(T) decode: prefill once, then one position per step against cached
K/V, everything jitted with static shapes).

Works straight off a `models.transformer.transformer_lm` checkpoint:
the decoder reads the SAME arg_params the training symbol binds
(tok_embed/pos_embed/layer{i}_*/final_ln/lm_head), re-expressing the
forward functionally so each step is one XLA program with
`lax.dynamic_update_slice` into a (L, B, H, max_len, dh) cache.
`tests/test_decode.py` pins step-by-step equivalence against the
symbol graph's full forward.

Beyond the shared-position API (`prefill`/`step`, every row at the same
``pos``), the decoder also exposes a **slot-pool API** for the serving
subsystem (`mxnet_tpu/serving/`): each batch row is an independent
*slot* with its own host-tracked ``(start, cursor)`` cache window, so
requests of different prompt lengths decode in ONE jitted step and
finished rows can be replaced mid-flight without touching the others —
see :meth:`KVDecoder.prefill_padded`, :meth:`KVDecoder.step_slots`, and
:meth:`KVDecoder.adopt_row`.  ``quantize="int8"`` stores the weights as
int8 + per-channel scales and dequantizes inside the compiled programs
(`serving/quantize.py`).
"""
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _snap(x, dtype=np.int32):
    """Device copy of host-side slot state (tokens/start/cursor/block
    tables) that can never alias the caller's buffer.  The CPU PJRT
    backend zero-copy-aliases suitably aligned numpy arrays on
    ``jnp.asarray``, so the steady-state idiom of mutating the host
    array in place right after an async dispatch (``cursor[b] += 1``,
    ``bt[b, idx] = page``) races with the still-executing program and
    flips its inputs mid-flight — the source of the long-standing
    serving bitwise-parity flake."""
    return jnp.asarray(np.array(x, dtype, copy=True))


def _count_compiles(fn, kind):
    """Wrap a to-be-jitted callable so each trace (= each XLA compile)
    lands in ``executor_compile_total{kind=decode_*}`` — the serving
    tests assert this stays flat after warmup (zero per-tick recompiles).
    """
    import functools

    from .. import telemetry as _tm

    ctr = _tm.counter(
        "executor_compile_total",
        "graph traces handed to XLA: one per jit cache miss, including "
        "per-shape recompiles", labels=("kind",))

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        ctr.inc(kind=kind)
        return fn(*args, **kwargs)

    return wrapper


class _DequantView(dict):
    """Param dict whose int8 entries dequantize on read.  Inside a jit
    trace the int8 array is the captured constant and the
    ``astype * scale`` fuses into the consumer (matmul/gather), so the
    device holds int8 storage while compute runs in the compute dtype."""

    def __getitem__(self, key):
        v = dict.__getitem__(self, key)
        deq = getattr(v, "dequantize", None)
        return deq() if deq is not None else v


def _logsumexp(x):
    m = x.max(-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(-1, keepdims=True))


def _ln(x, g, b, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * g + b


def _fc(x, w, b=None):
    y = x @ w.T
    return y if b is None else y + b


class KVDecoder:
    """One instance per (checkpoint, batch, max_len) combination.

    state = (k_cache, v_cache, pos):
      k/v_cache (L, B, H, max_len, dh); pos int32 — tokens filled so far.
    """

    def __init__(self, arg_params, num_layers, num_heads, max_len,
                 dtype=jnp.float32, mesh=None, model_axis="model",
                 quantize=None):
        """``mesh``: shard serving over devices, Megatron-style — q/k/v
        and ffn_in weights column-parallel, proj and ffn_out
        row-parallel, the K/V cache split on its HEAD axis — so each
        device holds 1/tp of the weights and cache and GSPMD inserts
        the one all-reduce per block the row-parallel products need
        (the serving mirror of parallel/mesh.megatron_rules)."""
        to = lambda a: jnp.asarray(
            a.asnumpy() if hasattr(a, "asnumpy") else a, dtype)
        p = {k: to(v) for k, v in arg_params.items()}
        self.mesh = mesh
        self.model_axis = model_axis
        if mesh is not None:
            from ..parallel.mesh import megatron_rules, shard_params

            tp = mesh.shape[model_axis]
            if num_heads % tp:
                raise ValueError(
                    f"num_heads {num_heads} must divide by the "
                    f"{model_axis!r} mesh axis ({tp})")
            for k, v in p.items():
                if k.endswith("_ffn_in_weight") and v.shape[0] % tp:
                    raise ValueError(
                        f"{k}: d_ff {v.shape[0]} must divide by the "
                        f"{model_axis!r} mesh axis ({tp})")
            # the training layout, minus the vocab-sharded head/embed
            # (decode keeps logits replicated — the sampler reads them
            # on the host every step)
            rules = tuple(r for r in megatron_rules(model_axis)
                          if "lm_head" not in r.pattern
                          and "tok_embed" not in r.pattern)
            p = shard_params(mesh, p, rules)
        self.L, self.H = num_layers, num_heads
        self.max_len = max_len
        self.d_model = p["tok_embed_weight"].shape[1]
        self.dh = self.d_model // num_heads
        self.vocab = p["lm_head_weight"].shape[0]
        self._cache_dtype = p["tok_embed_weight"].dtype
        if p["pos_embed"].shape[1] < max_len:
            raise ValueError(
                f"checkpoint pos table {p['pos_embed'].shape[1]} < "
                f"max_len {max_len}")
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {quantize!r} "
                             "(supported: 'int8')")
        if quantize == "int8":
            if mesh is not None:
                raise ValueError(
                    "quantize='int8' is not supported together with a "
                    "tensor-parallel mesh (shard the fp weights instead)")
            from ..serving.quantize import quantize_params

            p = _DequantView(quantize_params(p, dtype=dtype))
        self.quantize = quantize
        self.p = p
        self._step_jit = jax.jit(partial(self._forward_positions, n=1))
        self._reorder_jit = jax.jit(
            lambda kc, vc, idx: (kc[:, idx], vc[:, idx]))
        self._prefill_cache = {}
        self._scan_cache = {}
        self._padded_prefill_cache = {}
        self._slot_step_jit = jax.jit(
            _count_compiles(self._forward_slots, "decode_step"))
        # perf plane (telemetry/perf.py): one analytical cost row per
        # compiled decode program, captured at first dispatch
        self._cost_step_done = False
        self._cost_prefill_done = set()
        self._adopt_jit = jax.jit(_count_compiles(
            lambda kc, vc, kr, vr, slot: (
                jax.lax.dynamic_update_slice(kc, kr, (0, slot, 0, 0, 0)),
                jax.lax.dynamic_update_slice(vc, vr, (0, slot, 0, 0, 0))),
            "decode_adopt"))

    def _cache_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        # (L, B, H, max_len, dh): split the head axis
        return NamedSharding(self.mesh, P(None, None, self.model_axis))

    # ---------------------------------------------------------------- core
    def _block_qkv(self, i, h2):
        p = self.p
        name = f"layer{i}"
        q = _fc(h2, p[f"{name}_q_weight"], p[f"{name}_q_bias"])
        k = _fc(h2, p[f"{name}_k_weight"], p[f"{name}_k_bias"])
        v = _fc(h2, p[f"{name}_v_weight"], p[f"{name}_v_bias"])
        return q, k, v

    def _forward_positions(self, kc, vc, pos, tokens, n):
        """Run ``n`` new positions (tokens (B, n)) against the cache.
        ``pos`` rides as a traced scalar; the HOST tracks the counter so
        no step ever fetches device state (on tunneled backends a
        per-step sync would dominate decode latency)."""
        p = self.p
        B = tokens.shape[0]
        H, dh, D = self.H, self.dh, self.d_model

        tok = jnp.take(p["tok_embed_weight"], tokens.astype(jnp.int32),
                       axis=0)                       # (B, n, D)
        posv = jax.lax.dynamic_slice(
            p["pos_embed"], (0, pos, 0), (1, n, D))
        h = tok + posv
        # positions 0..max_len-1 valid iff < pos+ their offset
        span = pos + jnp.arange(n)                   # (n,)
        mask = jnp.arange(self.max_len)[None, :] <= span[:, None]  # (n, S)
        for i in range(self.L):
            name = f"layer{i}"
            h2 = _ln(h, p[f"{name}_ln1_gamma"], p[f"{name}_ln1_beta"])
            q, k, v = self._block_qkv(i, h2)
            sh = lambda a: a.reshape(B, n, H, dh).transpose(0, 2, 1, 3)
            qh, kh, vh = sh(q), sh(k), sh(v)         # (B, H, n, dh)
            kc = jax.lax.dynamic_update_slice(
                kc, kh[None], (i, 0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, vh[None], (i, 0, 0, pos, 0))
            scores = jnp.einsum("bhnd,bhsd->bhns", qh, kc[i]) \
                / jnp.sqrt(jnp.asarray(dh, h.dtype))
            scores = jnp.where(mask[None, None], scores, NEG_INF)
            att = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhns,bhsd->bhnd", att, vc[i])
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, n, D)
            proj = _fc(ctx, p[f"{name}_proj_weight"],
                       p[f"{name}_proj_bias"])
            h = h + proj
            h2 = _ln(h, p[f"{name}_ln2_gamma"], p[f"{name}_ln2_beta"])
            f = _fc(h2, p[f"{name}_ffn_in_weight"],
                    p[f"{name}_ffn_in_bias"])
            f = jax.nn.gelu(f)
            f = _fc(f, p[f"{name}_ffn_out_weight"],
                    p[f"{name}_ffn_out_bias"])
            h = h + f
        h = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
        logits = _fc(h, p["lm_head_weight"], p["lm_head_bias"])
        return (kc, vc), logits                      # logits (B, n, V)

    # ----------------------------------------------------------------- API
    def init_state(self, batch):
        """state = (k_cache, v_cache, pos) — pos is a HOST int."""
        shape = (self.L, batch, self.H, self.max_len, self.dh)
        dtype = self._cache_dtype
        if self.mesh is not None:
            # allocate SHARDED: each device holds 1/tp of the cache from
            # the start (a dense zeros + reshard would transiently put
            # the whole cache on one device)
            sh = self._cache_sharding()
            kc = jnp.zeros(shape, dtype, device=sh)
            vc = jnp.zeros(shape, dtype, device=sh)
        else:
            kc = jnp.zeros(shape, dtype)
            vc = jnp.zeros(shape, dtype)
        return (kc, vc, 0)

    def prefill(self, tokens):
        """tokens (B, T) -> (state, logits (B, T, V)); one compile per
        distinct prompt length."""
        tokens = jnp.asarray(tokens)
        B, T = tokens.shape
        if T > self.max_len:
            raise ValueError(f"prompt {T} > max_len {self.max_len}")
        if T not in self._prefill_cache:
            self._prefill_cache[T] = jax.jit(
                partial(self._forward_positions, n=T))
        kc, vc, pos = self.init_state(B)
        (kc, vc), logits = self._prefill_cache[T](kc, vc, pos, tokens)
        return (kc, vc, pos + T), logits

    def step(self, state, token):
        """token (B,) -> (state, logits (B, V)) — ONE fused XLA program
        per call, O(max_len) attention, no host-device sync."""
        kc, vc, pos = state
        if pos >= self.max_len:
            raise ValueError(
                f"cache full: {self.max_len} positions decoded (the "
                "checkpoint's positional table ends there)")
        (kc, vc), logits = self._step_jit(
            kc, vc, pos, jnp.asarray(token).reshape(-1, 1))
        return (kc, vc, pos + 1), logits[:, 0]

    # ------------------------------------------------- slot-pool API
    # (continuous batching, mxnet_tpu/serving/): each batch row is an
    # independent request slot whose cache window [start, cursor] the
    # CALLER tracks as host int arrays — no step reads device state, so
    # the scheduler's bookkeeping costs zero syncs, exactly like the
    # shared-pos API's host counter.  serving/paged_kv.py builds the
    # paged twin of these programs (block-table gather over a shared
    # page pool, same layer math via _block_qkv/_ln/_fc) — bitwise
    # equal to this path on aligned prompts, test-pinned.

    def _forward_slots(self, kc, vc, tokens, start, cursor):
        """One decode position for EVERY slot at once, each row at its
        own cache position.  ``tokens``/``start``/``cursor`` are (B,)
        int32: row ``b`` writes its new K/V at cache position
        ``cursor[b]`` and attends over ``[start[b], cursor[b]]`` with
        position embedding ``cursor[b] - start[b]``.  Rows whose slot is
        free still ride along (fixed batch keeps this ONE compiled
        program); their outputs are garbage the caller ignores and their
        writes land at position ``cursor[b]`` of a row :meth:`adopt_row`
        fully overwrites on the next admission."""
        p = self.p
        B = tokens.shape[0]
        H, dh, D = self.H, self.dh, self.d_model

        tok = jnp.take(p["tok_embed_weight"], tokens.astype(jnp.int32),
                       axis=0)                               # (B, D)
        pos_ids = jnp.clip(cursor - start, 0, self.max_len - 1)
        posv = jnp.take(p["pos_embed"][0], pos_ids, axis=0)  # (B, D)
        h = (tok + posv)[:, None]                            # (B, 1, D)
        s_idx = jnp.arange(self.max_len)
        valid = (s_idx[None, :] >= start[:, None]) & \
            (s_idx[None, :] <= cursor[:, None])              # (B, S)
        rows = jnp.arange(B)
        for i in range(self.L):
            name = f"layer{i}"
            h2 = _ln(h, p[f"{name}_ln1_gamma"], p[f"{name}_ln1_beta"])
            q, k, v = self._block_qkv(i, h2)
            sh = lambda a: a.reshape(B, 1, H, dh).transpose(0, 2, 1, 3)
            qh, kh, vh = sh(q), sh(k), sh(v)                 # (B, H, 1, dh)
            kc = kc.at[i, rows, :, cursor].set(kh[:, :, 0])
            vc = vc.at[i, rows, :, cursor].set(vh[:, :, 0])
            scores = jnp.einsum("bhnd,bhsd->bhns", qh, kc[i]) \
                / jnp.sqrt(jnp.asarray(dh, h.dtype))
            scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
            att = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhns,bhsd->bhnd", att, vc[i])
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, 1, D)
            proj = _fc(ctx, p[f"{name}_proj_weight"],
                       p[f"{name}_proj_bias"])
            h = h + proj
            h2 = _ln(h, p[f"{name}_ln2_gamma"], p[f"{name}_ln2_beta"])
            f = _fc(h2, p[f"{name}_ffn_in_weight"],
                    p[f"{name}_ffn_in_bias"])
            f = jax.nn.gelu(f)
            f = _fc(f, p[f"{name}_ffn_out_weight"],
                    p[f"{name}_ffn_out_bias"])
            h = h + f
        h = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
        logits = _fc(h, p["lm_head_weight"], p["lm_head_bias"])
        return (kc, vc), logits[:, 0]                        # (B, V)

    def _forward_padded(self, kc, vc, tokens, start):
        """Left-padded prefill: ``tokens`` (B, T) with row ``b``'s real
        prompt right-aligned in the last ``T - start[b]`` positions.
        Real tokens write K/V at their padded index and attend over
        ``[start[b], n]``; pad queries (n < start) attend to themselves
        only — finite garbage that every real query's window excludes.
        Left-padding makes ``logits[:, -1]`` the next-token logits of
        EVERY row regardless of its prompt length."""
        p = self.p
        B, T = tokens.shape
        H, dh, D = self.H, self.dh, self.d_model

        tok = jnp.take(p["tok_embed_weight"], tokens.astype(jnp.int32),
                       axis=0)                               # (B, T, D)
        pos_ids = jnp.clip(jnp.arange(T)[None, :] - start[:, None],
                           0, self.max_len - 1)              # (B, T)
        posv = jnp.take(p["pos_embed"][0], pos_ids, axis=0)  # (B, T, D)
        h = tok + posv
        n_idx = jnp.arange(T)
        s_idx = jnp.arange(self.max_len)
        lo = jnp.minimum(start[:, None], n_idx[None, :])     # (B, T)
        valid = (s_idx[None, None, :] <= n_idx[None, :, None]) & \
            (s_idx[None, None, :] >= lo[:, :, None])         # (B, T, S)
        for i in range(self.L):
            name = f"layer{i}"
            h2 = _ln(h, p[f"{name}_ln1_gamma"], p[f"{name}_ln1_beta"])
            q, k, v = self._block_qkv(i, h2)
            sh = lambda a: a.reshape(B, T, H, dh).transpose(0, 2, 1, 3)
            qh, kh, vh = sh(q), sh(k), sh(v)                 # (B, H, T, dh)
            kc = jax.lax.dynamic_update_slice(kc, kh[None], (i, 0, 0, 0, 0))
            vc = jax.lax.dynamic_update_slice(vc, vh[None], (i, 0, 0, 0, 0))
            scores = jnp.einsum("bhnd,bhsd->bhns", qh, kc[i]) \
                / jnp.sqrt(jnp.asarray(dh, h.dtype))
            scores = jnp.where(valid[:, None], scores, NEG_INF)
            att = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhns,bhsd->bhnd", att, vc[i])
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, T, D)
            proj = _fc(ctx, p[f"{name}_proj_weight"],
                       p[f"{name}_proj_bias"])
            h = h + proj
            h2 = _ln(h, p[f"{name}_ln2_gamma"], p[f"{name}_ln2_beta"])
            f = _fc(h2, p[f"{name}_ffn_in_weight"],
                    p[f"{name}_ffn_in_bias"])
            f = jax.nn.gelu(f)
            f = _fc(f, p[f"{name}_ffn_out_weight"],
                    p[f"{name}_ffn_out_bias"])
            h = h + f
        h = _ln(h, p["final_ln_gamma"], p["final_ln_beta"])
        logits = _fc(h, p["lm_head_weight"], p["lm_head_bias"])
        return (kc, vc), logits                              # (B, T, V)

    def init_slot_state(self, num_slots):
        """Empty slot-pool cache ``(k_cache, v_cache)`` for ``num_slots``
        slots; the per-slot ``start``/``cursor`` windows live with the
        caller (host int arrays)."""
        kc, vc, _ = self.init_state(num_slots)
        return kc, vc

    def prefill_padded(self, tokens, lengths):
        """Variable-length co-batched prefill.  ``tokens`` (B, T)
        LEFT-padded, ``lengths`` (B,) real prompt lengths (0 < len <= T).
        Returns ``((kc, vc), logits)`` with logits (B, T, V);
        ``logits[:, -1]`` is every row's next-token distribution.  The
        caller's slot windows are ``start = T - lengths``, ``cursor = T``.
        One compile per distinct padded length T (bucket prompt lengths
        to bound the program count)."""
        tokens = jnp.asarray(tokens)
        B, T = tokens.shape
        lengths = np.asarray(lengths, np.int64)
        if T > self.max_len:
            raise ValueError(f"padded prompt {T} > max_len {self.max_len}")
        if lengths.shape != (B,) or (lengths <= 0).any() \
                or (lengths > T).any():
            raise ValueError(
                f"lengths must be (B,) in [1, {T}], got {lengths!r}")
        if T not in self._padded_prefill_cache:
            self._padded_prefill_cache[T] = jax.jit(
                _count_compiles(self._forward_padded, "decode_prefill"))
        kc, vc, _ = self.init_state(B)
        start = (T - lengths).astype(np.int32)
        (kc, vc), logits = self._padded_prefill_cache[T](
            kc, vc, tokens, jnp.asarray(start))
        if T not in self._cost_prefill_done:
            from .. import telemetry as _tm

            if _tm.perf.enabled():
                self._cost_prefill_done.add(T)
                _tm.perf.attach_cost_analysis(
                    f"decode_prefill[b{T}]",
                    self._padded_prefill_cache[T],
                    kc, vc, tokens, jnp.asarray(start))
        return (kc, vc), logits

    def step_slots(self, cache, tokens, start, cursor):
        """One decode tick over the whole slot pool: (B,) next tokens in,
        ``((kc, vc), logits (B, V))`` out.  ``start``/``cursor`` are the
        host-tracked per-slot cache windows; the caller advances
        ``cursor[b] += 1`` for every row it actually consumed and MUST
        keep ``cursor < max_len`` (finish the request when its window is
        full).  ONE fused XLA program regardless of which slots are
        live."""
        kc, vc = cache
        cursor = np.asarray(cursor)
        if (cursor >= self.max_len).any():
            raise ValueError(
                f"slot cursor at max_len {self.max_len}: finish or evict "
                "the request before ticking it")
        (kc, vc), logits = self._slot_step_jit(
            kc, vc, _snap(tokens), _snap(start), _snap(cursor))
        if not self._cost_step_done:
            from .. import telemetry as _tm

            if _tm.perf.enabled():
                self._cost_step_done = True
                _tm.perf.attach_cost_analysis(
                    "decode_step_slots", self._slot_step_jit,
                    kc, vc, _snap(tokens), _snap(start), _snap(cursor))
        return (kc, vc), logits

    def adopt_row(self, cache, row_cache, slot):
        """Copy a freshly prefilled batch-1 cache (from
        :meth:`prefill_padded` at B=1) into slot ``slot`` of the pool —
        the admission write of the continuous-batching scheduler.  The
        slot index rides as a traced scalar, so every admission reuses
        ONE compiled program."""
        kc, vc = cache
        kr, vr = row_cache
        if kr.shape[1] != 1:
            raise ValueError(f"row cache must be batch-1, got {kr.shape}")
        kc, vc = self._adopt_jit(kc, vc, kr, vr, jnp.int32(slot))
        return kc, vc

    def _check_generation_budget(self, prompt, n_tokens):
        """Shared generate()/generate_scan() prologue: normalized prompt
        plus the empty-result short-circuit (None when real work remains)."""
        prompt = np.asarray(prompt)
        total = prompt.shape[1] + n_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt+n_tokens = {total} exceeds max_len "
                f"{self.max_len} (the checkpoint's positional table)")
        empty = (np.zeros((prompt.shape[0], 0), np.int64)
                 if n_tokens <= 0 else None)
        return prompt, empty

    def generate(self, prompt, n_tokens, temperature=1.0, top_k=None,
                 rng=None):
        """Greedy/temperature sampling loop; returns (B, n_tokens)."""
        rng = rng or np.random.RandomState(0)
        prompt, empty = self._check_generation_budget(prompt, n_tokens)
        if empty is not None:
            return empty
        state, logits = self.prefill(prompt)
        last = logits[:, -1]
        out = []
        for i in range(n_tokens):
            lg = np.asarray(last, np.float32)
            if temperature <= 0:
                nxt = lg.argmax(-1)
            else:
                lg = lg / temperature
                if top_k:
                    kth = np.partition(lg, -top_k, axis=-1)[:, -top_k, None]
                    lg = np.where(lg < kth, -np.inf, lg)
                z = lg - lg.max(-1, keepdims=True)
                prob = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
                nxt = np.array([rng.choice(lg.shape[-1], p=p_)
                                for p_ in prob])
            out.append(nxt)
            if i + 1 < n_tokens:  # the last sampled token needs no step
                state, last = self.step(state, nxt)
        return np.stack(out, axis=1)

    def generate_scan(self, prompt, n_tokens, temperature=0.0,
                      top_k=None, seed=0, eos_id=None):
        """generate(), but the WHOLE autoregressive loop is one compiled
        lax.scan — one dispatch for n_tokens steps instead of one per
        token.  On high-latency links (the bench tunnel) per-token
        dispatch dominates decode throughput the same way it dominated
        small-batch training (trainer.step_multi); on a local host it
        simply removes n-1 dispatches.  Greedy when temperature<=0,
        otherwise categorical sampling (jax.random, seeded) with
        optional static top_k.  Token-for-token equal to generate() in
        greedy mode (pinned by tests/test_decode.py).

        With ``eos_id``, rows that emit it are eos-padded from then on
        (beam_search's convention) and the loop becomes a
        lax.while_loop that EXITS as soon as every row has finished —
        early stopping happens on device, still within the single
        dispatch."""
        prompt, empty = self._check_generation_budget(prompt, n_tokens)
        if empty is not None:
            return empty
        state, logits = self.prefill(prompt)
        kc, vc, pos = state
        key = (prompt.shape[0], n_tokens, float(temperature),
               top_k or 0, eos_id if eos_id is not None else -1)
        fn = self._scan_cache.get(key)
        if fn is None:
            greedy = temperature <= 0

            def pick(lg, k_):
                if top_k:
                    kth = jax.lax.top_k(lg, top_k)[0][..., -1:]
                    lg = jnp.where(lg < kth, NEG_INF, lg)
                if greedy:
                    return jnp.argmax(lg, axis=-1)
                return jax.random.categorical(k_, lg / temperature)

            def step_once(kc, vc, pos, tok, k_):
                """ONE decode position + next-token pick — shared by the
                scan and while_loop bodies so they cannot diverge."""
                (kc, vc), lg = self._forward_positions(
                    kc, vc, pos, tok[:, None], n=1)
                k_, sub = jax.random.split(k_)
                return kc, vc, pick(lg[:, 0], sub), k_

            def loop(kc, vc, pos0, last_logits, rng_key):
                k0, krest = jax.random.split(rng_key)
                first = pick(last_logits, k0)

                def body(carry, i):
                    kc, vc, tok, k_ = carry
                    kc, vc, nxt, k_ = step_once(kc, vc, pos0 + i, tok, k_)
                    return (kc, vc, nxt, k_), nxt

                (kc, vc, _, _), rest = jax.lax.scan(
                    body, (kc, vc, first, krest),
                    jnp.arange(n_tokens - 1, dtype=jnp.int32))
                toks = jnp.concatenate(
                    [first[:, None], rest.transpose(1, 0)], axis=1)
                return kc, vc, toks

            def loop_eos(kc, vc, pos0, last_logits, rng_key):
                B = last_logits.shape[0]
                k0, krest = jax.random.split(rng_key)
                first = pick(last_logits, k0)
                done0 = first == eos_id
                buf = jnp.full((n_tokens, B), eos_id, jnp.int32)
                buf = buf.at[0].set(first.astype(jnp.int32))

                def cond(carry):
                    i, kc, vc, tok, k_, done, buf = carry
                    return jnp.logical_and(i < n_tokens - 1,
                                           jnp.logical_not(done.all()))

                def body(carry):
                    i, kc, vc, tok, k_, done, buf = carry
                    kc, vc, nxt, k_ = step_once(kc, vc, pos0 + i, tok, k_)
                    nxt = jnp.where(done, eos_id, nxt)  # freeze finished
                    done = jnp.logical_or(done, nxt == eos_id)
                    buf = buf.at[i + 1].set(nxt.astype(jnp.int32))
                    return (i + 1, kc, vc, nxt, k_, done, buf)

                (_, kc, vc, _, _, _, buf) = jax.lax.while_loop(
                    cond, body,
                    (jnp.int32(0), kc, vc, first, krest, done0, buf))
                return kc, vc, buf.transpose(1, 0)

            fn = jax.jit(loop if eos_id is None else loop_eos)
            self._scan_cache[key] = fn
        kc, vc, toks = fn(kc, vc, jnp.int32(pos),
                          logits[:, -1].astype(jnp.float32),
                          jax.random.PRNGKey(seed))
        return np.asarray(toks, np.int64)

    def beam_search(self, prompt, n_tokens, beam_size=4,
                    length_penalty=0.0, eos_id=None):
        """Beam decode: returns (tokens (B, beam, n_tokens),
        scores (B, beam)) sorted best-first per batch row.

        With ``eos_id`` set, beams that emit it stop accumulating score
        (further positions are eos-padded) and ``length_penalty``
        normalizes each beam's score by its OWN length^penalty — the
        standard way longer unfinished beams compete with short
        finished ones.  Without an eos, every beam has equal length and
        the penalty only rescales scores.

        The cache runs at batch B*beam from the start (prompt rows
        replicated); beam reordering is a jitted row-gather on the
        device cache, the bookkeeping (log-probs, back-pointers) stays
        host-side like the sampling loop."""
        prompt = np.asarray(prompt)
        B, T = prompt.shape
        if T + n_tokens > self.max_len:
            raise ValueError(
                f"prompt+n_tokens = {T + n_tokens} exceeds max_len "
                f"{self.max_len}")
        if beam_size > self.vocab:
            raise ValueError(
                f"beam_size {beam_size} > vocab {self.vocab}")
        if n_tokens <= 0:
            return (np.zeros((B, beam_size, 0), np.int64),
                    np.zeros((B, beam_size), np.float32))
        K = beam_size

        def topk(mat, k):
            part = np.argpartition(-mat, k - 1, axis=-1)[:, :k]
            vals = np.take_along_axis(mat, part, axis=-1)
            order = np.argsort(-vals, axis=-1)
            return np.take_along_axis(part, order, axis=-1)

        state, logits = self.prefill(np.repeat(prompt, K, axis=0))
        last = np.asarray(logits[:, -1], np.float32)     # (B*K, V)
        V = last.shape[-1]
        logp = last - _logsumexp(last)
        # first expansion: distinct top-K continuations per batch row
        first = logp.reshape(B, K, V)[:, 0]              # replicas identical
        top = topk(first, K)                             # (B, K)
        scores = np.take_along_axis(first, top, axis=-1)  # (B, K)
        seqs = top[:, :, None]                           # (B, K, 1)
        finished = (top == eos_id) if eos_id is not None \
            else np.zeros((B, K), bool)
        lengths = np.ones((B, K), np.int64)
        nxt = top.reshape(-1)
        for i in range(1, n_tokens):
            if finished.all():
                pad = np.full((B, K, n_tokens - i), eos_id, np.int64)
                seqs = np.concatenate([seqs, pad], axis=2)
                break
            state, lg = self.step(state, nxt)
            logp = np.asarray(lg, np.float32)
            logp = (logp - _logsumexp(logp)).reshape(B, K, V)
            cand = scores[:, :, None] + logp             # (B, K, V)
            if eos_id is not None:
                # a finished beam contributes exactly one candidate:
                # itself, eos-padded, score frozen
                cand[finished] = NEG_INF
                cand[finished, eos_id] = scores[finished]
            flat = cand.reshape(B, K * V)
            top = topk(flat, K)                          # (B, K)
            beam_idx, tok = top // V, top % V
            scores = np.take_along_axis(flat, top, axis=-1)
            seqs = np.concatenate(
                [np.take_along_axis(seqs, beam_idx[:, :, None], axis=1),
                 tok[:, :, None]], axis=2)
            parent_fin = np.take_along_axis(finished, beam_idx, axis=-1)
            lengths = np.take_along_axis(lengths, beam_idx, axis=-1) \
                + (~parent_fin)
            if eos_id is not None:
                finished = parent_fin | (tok == eos_id)
            nxt = tok.reshape(-1)
            if i + 1 < n_tokens and not finished.all():
                # follow the survivors on the device cache (skipped on
                # the last step — nothing consumes it)
                rows = (np.arange(B)[:, None] * K + beam_idx).reshape(-1)
                kc, vc, pos = state
                kc, vc = self._reorder_jit(kc, vc, jnp.asarray(rows))
                state = (kc, vc, pos)
        if length_penalty:
            scores = scores / (lengths.astype(np.float32)
                               ** length_penalty)
        order = np.argsort(-scores, axis=-1)
        return (np.take_along_axis(seqs, order[:, :, None], axis=1),
                np.take_along_axis(scores, order, axis=-1))
