"""Inception-ResNet-v2 (parity:
example/image-classification/symbols/inception-resnet-v2.py)."""
from .. import symbol as sym


def ConvFactory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                act_type="relu", mirror_attr=None, with_act=True, name=None):
    conv = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, name=name)
    bn = sym.BatchNorm(conv, name=f"{name}_bn" if name else None)
    if with_act:
        return sym.Activation(bn, act_type=act_type,
                              name=f"{name}_relu" if name else None)
    return bn


def block35(net, input_num_channels, scale=1.0, with_act=True, name=None):
    tower_conv = ConvFactory(net, 32, (1, 1), name=f"{name}_t1_c1")
    tower_conv1_0 = ConvFactory(net, 32, (1, 1), name=f"{name}_t2_c1")
    tower_conv1_1 = ConvFactory(tower_conv1_0, 32, (3, 3), pad=(1, 1),
                                name=f"{name}_t2_c2")
    tower_conv2_0 = ConvFactory(net, 32, (1, 1), name=f"{name}_t3_c1")
    tower_conv2_1 = ConvFactory(tower_conv2_0, 48, (3, 3), pad=(1, 1),
                                name=f"{name}_t3_c2")
    tower_conv2_2 = ConvFactory(tower_conv2_1, 64, (3, 3), pad=(1, 1),
                                name=f"{name}_t3_c3")
    tower_mixed = sym.Concat(tower_conv, tower_conv1_1, tower_conv2_2)
    tower_out = ConvFactory(tower_mixed, input_num_channels, (1, 1),
                            with_act=False, name=f"{name}_out")
    net = net + scale * tower_out
    if with_act:
        net = sym.Activation(net, act_type="relu")
    return net


def block17(net, input_num_channels, scale=1.0, with_act=True, name=None):
    tower_conv = ConvFactory(net, 192, (1, 1), name=f"{name}_t1_c1")
    tower_conv1_0 = ConvFactory(net, 129, (1, 1), name=f"{name}_t2_c1")
    tower_conv1_1 = ConvFactory(tower_conv1_0, 160, (1, 7), pad=(1, 2),
                                name=f"{name}_t2_c2")
    tower_conv1_2 = ConvFactory(tower_conv1_1, 192, (7, 1), pad=(2, 1),
                                name=f"{name}_t2_c3")
    tower_mixed = sym.Concat(tower_conv, tower_conv1_2)
    tower_out = ConvFactory(tower_mixed, input_num_channels, (1, 1),
                            with_act=False, name=f"{name}_out")
    net = net + scale * tower_out
    if with_act:
        net = sym.Activation(net, act_type="relu")
    return net


def block8(net, input_num_channels, scale=1.0, with_act=True, name=None):
    tower_conv = ConvFactory(net, 192, (1, 1), name=f"{name}_t1_c1")
    tower_conv1_0 = ConvFactory(net, 192, (1, 1), name=f"{name}_t2_c1")
    tower_conv1_1 = ConvFactory(tower_conv1_0, 224, (1, 3), pad=(0, 1),
                                name=f"{name}_t2_c2")
    tower_conv1_2 = ConvFactory(tower_conv1_1, 256, (3, 1), pad=(1, 0),
                                name=f"{name}_t2_c3")
    tower_mixed = sym.Concat(tower_conv, tower_conv1_2)
    tower_out = ConvFactory(tower_mixed, input_num_channels, (1, 1),
                            with_act=False, name=f"{name}_out")
    net = net + scale * tower_out
    if with_act:
        net = sym.Activation(net, act_type="relu")
    return net


def repeat(inputs, repetitions, layer, *args, name=None, **kwargs):
    outputs = inputs
    for i in range(repetitions):
        outputs = layer(outputs, *args, name=f"{name}_{i}", **kwargs)
    return outputs


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    conv1a_3_3 = ConvFactory(data, 32, (3, 3), stride=(2, 2),
                             name="conv1a_3_3")
    conv2a_3_3 = ConvFactory(conv1a_3_3, 32, (3, 3), name="conv2a_3_3")
    conv2b_3_3 = ConvFactory(conv2a_3_3, 64, (3, 3), pad=(1, 1),
                             name="conv2b_3_3")
    maxpool3a_3_3 = sym.Pooling(conv2b_3_3, kernel=(3, 3), stride=(2, 2),
                                pool_type="max")
    conv3b_1_1 = ConvFactory(maxpool3a_3_3, 80, (1, 1), name="conv3b_1_1")
    conv4a_3_3 = ConvFactory(conv3b_1_1, 192, (3, 3), name="conv4a_3_3")
    maxpool5a_3_3 = sym.Pooling(conv4a_3_3, kernel=(3, 3), stride=(2, 2),
                                pool_type="max")

    tower_conv = ConvFactory(maxpool5a_3_3, 96, (1, 1), name="tower_conv")
    tower_conv1_0 = ConvFactory(maxpool5a_3_3, 48, (1, 1),
                                name="tower_conv1_0")
    tower_conv1_1 = ConvFactory(tower_conv1_0, 64, (5, 5), pad=(2, 2),
                                name="tower_conv1_1")
    tower_conv2_0 = ConvFactory(maxpool5a_3_3, 64, (1, 1),
                                name="tower_conv2_0")
    tower_conv2_1 = ConvFactory(tower_conv2_0, 96, (3, 3), pad=(1, 1),
                                name="tower_conv2_1")
    tower_conv2_2 = ConvFactory(tower_conv2_1, 96, (3, 3), pad=(1, 1),
                                name="tower_conv2_2")
    tower_pool3_0 = sym.Pooling(maxpool5a_3_3, kernel=(3, 3), stride=(1, 1),
                                pad=(1, 1), pool_type="avg")
    tower_conv3_1 = ConvFactory(tower_pool3_0, 64, (1, 1),
                                name="tower_conv3_1")
    tower_5b_out = sym.Concat(tower_conv, tower_conv1_1, tower_conv2_2,
                              tower_conv3_1)

    net = repeat(tower_5b_out, 10, block35, 320, scale=0.17, name="block35")

    tower_conv = ConvFactory(net, 384, (3, 3), stride=(2, 2), name="rd1_t1")
    tower_conv1_0 = ConvFactory(net, 256, (1, 1), name="rd1_t2_c1")
    tower_conv1_1 = ConvFactory(tower_conv1_0, 256, (3, 3), pad=(1, 1),
                                name="rd1_t2_c2")
    tower_conv1_2 = ConvFactory(tower_conv1_1, 384, (3, 3), stride=(2, 2),
                                name="rd1_t2_c3")
    tower_pool = sym.Pooling(net, kernel=(3, 3), stride=(2, 2),
                             pool_type="max")
    net = sym.Concat(tower_conv, tower_conv1_2, tower_pool)

    net = repeat(net, 20, block17, 1088, scale=0.1, name="block17")

    tower_conv = ConvFactory(net, 256, (1, 1), name="rd2_t1_c1")
    tower_conv0_1 = ConvFactory(tower_conv, 384, (3, 3), stride=(2, 2),
                                name="rd2_t1_c2")
    tower_conv1 = ConvFactory(net, 256, (1, 1), name="rd2_t2_c1")
    tower_conv1_1 = ConvFactory(tower_conv1, 288, (3, 3), stride=(2, 2),
                                name="rd2_t2_c2")
    tower_conv2 = ConvFactory(net, 256, (1, 1), name="rd2_t3_c1")
    tower_conv2_1 = ConvFactory(tower_conv2, 288, (3, 3), pad=(1, 1),
                                name="rd2_t3_c2")
    tower_conv2_2 = ConvFactory(tower_conv2_1, 320, (3, 3), stride=(2, 2),
                                name="rd2_t3_c3")
    tower_pool = sym.Pooling(net, kernel=(3, 3), stride=(2, 2),
                             pool_type="max")
    net = sym.Concat(tower_conv0_1, tower_conv1_1, tower_conv2_2, tower_pool)

    net = repeat(net, 9, block8, 2080, scale=0.2, name="block8")
    net = block8(net, 2080, with_act=False, name="block8_final")

    net = ConvFactory(net, 1536, (1, 1), name="conv6_1_1")
    net = sym.Pooling(net, kernel=(8, 8), stride=(1, 1), global_pool=True,
                      pool_type="avg", name="global_pool")
    net = sym.Flatten(net, name="flatten")
    net = sym.Dropout(net, p=0.2)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                             name="softmax")
