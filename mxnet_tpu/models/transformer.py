"""Decoder-only Transformer language model (beyond-reference: the
reference's sequence story tops out at bucketed LSTMs, SURVEY.md §5.7).

Built from the same symbol API as every other model-zoo entry, with the
long-context pieces this framework treats as first-class: causal
FlashAttention (Pallas kernel, ops/flash_attention.py) inside the block,
LayerNorm/gelu (ops/nn.py), and — for sequence lengths beyond one chip —
the same attention math is available sharded over a mesh via
parallel/ring_attention.py.

`transformer_lm(...)` returns the training symbol; pair it with
FusedTrainer for the fused train step (examples/transformer-lm/).
"""
from .. import symbol as sym


def _attention_block(h, seq_len, d_model, num_heads, name):
    dh = d_model // num_heads
    ln = sym.LayerNorm(h, name=f"{name}_ln1")
    x2 = sym.Reshape(ln, shape=(-1, d_model))

    # separate q/k/v projections (not one fused 3*d_model FC): under
    # Megatron TP each (d_model, d_model) weight row-shards cleanly on
    # the 'model' axis, whereas a fused qkv shard boundary would cut
    # through the packed q|k|v layout and force GSPMD to re-gather the
    # activation before the head split (parallel/mesh.py megatron_rules)
    def heads(proj_name):
        p = sym.FullyConnected(x2, num_hidden=d_model, name=proj_name)
        p = sym.Reshape(p, shape=(-1, seq_len, num_heads, dh))
        return sym.transpose(p, axes=(0, 2, 1, 3))  # (N, H, T, Dh)

    att = sym.FlashAttention(heads(f"{name}_q"), heads(f"{name}_k"),
                             heads(f"{name}_v"),
                             causal=True, name=f"{name}_attn")
    att = sym.transpose(att, axes=(0, 2, 1, 3))
    att = sym.Reshape(att, shape=(-1, d_model))
    proj = sym.FullyConnected(att, num_hidden=d_model, name=f"{name}_proj")
    proj = sym.Reshape(proj, shape=(-1, seq_len, d_model))
    return h + proj


def _ffn_block(h, seq_len, d_model, d_ff, name, dropout):
    ln = sym.LayerNorm(h, name=f"{name}_ln2")
    x2 = sym.Reshape(ln, shape=(-1, d_model))
    f = sym.FullyConnected(x2, num_hidden=d_ff, name=f"{name}_ffn_in")
    f = sym.Activation(f, act_type="gelu")
    if dropout > 0:
        f = sym.Dropout(f, p=dropout)
    f = sym.FullyConnected(f, num_hidden=d_model, name=f"{name}_ffn_out")
    f = sym.Reshape(f, shape=(-1, seq_len, d_model))
    return h + f


def transformer_lm(num_layers=4, num_heads=4, d_model=128, d_ff=None,
                   seq_len=128, vocab_size=1000, dropout=0.0,
                   ignore_label=None, max_len=None):
    """Next-token LM: data (N, T) token ids, softmax_label (N, T).

    ignore_label masks padding out of the loss/gradient, and max_len
    sizes the positional table independently of this bucket's seq_len —
    together they make the symbol bucketing-ready (BucketingModule
    shares one pos_embed across all sequence-length buckets)."""
    if d_model % num_heads:
        raise ValueError("d_model must divide by num_heads")
    d_ff = d_ff or 4 * d_model
    max_len = max_len or seq_len
    if max_len < seq_len:
        raise ValueError("max_len must be >= seq_len")
    data = sym.Variable("data")
    tok = sym.Embedding(data, input_dim=vocab_size, output_dim=d_model,
                        name="tok_embed")
    pos = sym.Variable("pos_embed", shape=(1, max_len, d_model))
    if max_len != seq_len:
        pos = sym.slice_axis(pos, axis=1, begin=0, end=seq_len)
    h = sym.broadcast_add(tok, pos)
    for i in range(num_layers):
        h = _attention_block(h, seq_len, d_model, num_heads, f"layer{i}")
        h = _ffn_block(h, seq_len, d_model, d_ff, f"layer{i}", dropout)
    h = sym.LayerNorm(h, name="final_ln")
    h = sym.Reshape(h, shape=(-1, d_model))
    logits = sym.FullyConnected(h, num_hidden=vocab_size, name="lm_head")
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    loss_kw = {}
    if ignore_label is not None:
        loss_kw = {"use_ignore": True, "ignore_label": ignore_label,
                   "normalization": "valid"}
    return sym.SoftmaxOutput(logits, label, name="softmax", **loss_kw)


def get_symbol(num_classes=1000, **kwargs):
    kwargs.setdefault("vocab_size", num_classes)
    return transformer_lm(**kwargs)


# ---------------------------------------------------------------------------
# MFU accounting — the ONE definition bench.py and tools/probe_lm_mfu.py
# share, so the bench extra and the probe sweep can never desynchronize.
# ---------------------------------------------------------------------------

# the compute-bound headline config (~220M params): big enough matmuls to
# feed the MXU, small enough that Adam state + activations fit one v5e
# chosen by the on-silicon sweep (docs/measured/lmmfu_r05.txt): the
# d2048 8-layer config more than doubles the d1024 12-layer's MFU
# (0.47-0.53 vs 0.24 at b8 on v5e) — wider matmuls feed the MXU better
# than more layers at the same parameter budget
MFU_HEADLINE_CONFIG = dict(num_layers=8, num_heads=16, d_model=2048,
                           d_ff=8192, seq_len=1024, vocab_size=32768)


def lm_train_flops_per_token(num_layers, d_model, d_ff, seq_len,
                             vocab_size):
    """Model-FLOP cost of ONE training token, conservative accounting:
    6 * matmul-params (qkv/proj, ffn, head; embedding gathers are free)
    plus causal-halved flash attention (6*L*T*D — the Pallas kernel
    skips fully-masked key blocks, ops/flash_attention.py:48-63)."""
    n_mat = (num_layers * (4 * d_model * d_model + 2 * d_model * d_ff)
             + d_model * vocab_size)
    return 6 * n_mat + 6 * num_layers * seq_len * d_model
