"""ResNeXt (parity: the grouped-convolution variant of resnet.py; the
reference tracks it as a BASELINE.md conv-stress config)."""
from .. import symbol as sym


def resnext_unit(data, num_filter, stride, dim_match, name, num_group=32,
                 bn_mom=0.9):
    bn1 = sym.BatchNorm(data, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name=name + "_bn1")
    act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
    conv1 = sym.Convolution(act1, num_filter=num_filter // 2, kernel=(1, 1),
                            stride=(1, 1), pad=(0, 0), no_bias=True,
                            name=name + "_conv1")
    bn2 = sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name=name + "_bn2")
    act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
    conv2 = sym.Convolution(act2, num_filter=num_filter // 2, num_group=num_group,
                            kernel=(3, 3), stride=stride, pad=(1, 1),
                            no_bias=True, name=name + "_conv2")
    bn3 = sym.BatchNorm(conv2, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name=name + "_bn3")
    act3 = sym.Activation(bn3, act_type="relu", name=name + "_relu3")
    conv3 = sym.Convolution(act3, num_filter=num_filter, kernel=(1, 1),
                            stride=(1, 1), pad=(0, 0), no_bias=True,
                            name=name + "_conv3")
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(act1, num_filter=num_filter, kernel=(1, 1),
                                   stride=stride, no_bias=True, name=name + "_sc")
    return conv3 + shortcut


def get_symbol(num_classes=1000, num_layers=101, num_group=32,
               image_shape=(3, 224, 224), bn_mom=0.9, **kwargs):
    units_map = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
    if num_layers not in units_map:
        raise ValueError(f"unsupported resnext depth {num_layers}")
    units = units_map[num_layers]
    filter_list = [64, 256, 512, 1024, 2048]

    data = sym.Variable("data")
    body = sym.Convolution(data, num_filter=filter_list[0], kernel=(7, 7),
                           stride=(2, 2), pad=(3, 3), no_bias=True, name="conv0")
    body = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                         name="bn0")
    body = sym.Activation(body, act_type="relu", name="relu0")
    body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    for i in range(4):
        body = resnext_unit(body, filter_list[i + 1], (1 if i == 0 else 2,) * 2,
                            False, name=f"stage{i + 1}_unit1",
                            num_group=num_group, bn_mom=bn_mom)
        for j in range(units[i] - 1):
            body = resnext_unit(body, filter_list[i + 1], (1, 1), True,
                                name=f"stage{i + 1}_unit{j + 2}",
                                num_group=num_group, bn_mom=bn_mom)
    bn1 = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name="bn1")
    relu1 = sym.Activation(bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(relu1, global_pool=True, kernel=(7, 7), pool_type="avg",
                        name="pool1")
    flat = sym.Flatten(pool1)
    fc1 = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc1, name="softmax")
