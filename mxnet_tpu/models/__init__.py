"""Model zoo — symbol builders for the reference's example networks.

Parity: example/image-classification/symbols/ (reference): mlp, lenet,
alexnet, vgg, inception-bn, inception-v3, resnet, resnext + the rnn/lstm
examples.  Each get_symbol returns a Symbol ending in SoftmaxOutput named
'softmax', matching the reference training scripts' expectations.
"""
from . import (mlp, lenet, alexnet, vgg, googlenet, inception_bn,
               inception_v3, inception_resnet, resnet, resnext, lstm, ssd,
               transformer)


def get_symbol(name, num_classes=1000, **kwargs):
    """Parity: example/image-classification/train_model.py symbol dispatch."""
    builders = {
        "mlp": mlp.get_symbol,
        "lenet": lenet.get_symbol,
        "alexnet": alexnet.get_symbol,
        "vgg": vgg.get_symbol,
        "googlenet": googlenet.get_symbol,
        "inception-bn": inception_bn.get_symbol,
        "inception-v3": inception_v3.get_symbol,
        "inception-resnet-v2": inception_resnet.get_symbol,
        "resnet": resnet.get_symbol,
        "resnext": resnext.get_symbol,
        "ssd-vgg16": ssd.get_symbol_train,
        "transformer-lm": transformer.get_symbol,
    }
    if name.startswith("resnet-"):
        return resnet.get_symbol(num_classes, num_layers=int(name.split("-")[1]), **kwargs)
    if name.startswith("resnext-"):
        return resnext.get_symbol(num_classes, num_layers=int(name.split("-")[1]), **kwargs)
    if name not in builders:
        raise ValueError(f"unknown model {name}; have {sorted(builders)}")
    return builders[name](num_classes, **kwargs)
