"""VGG-16 (parity: example/image-classification/symbols/vgg.py)."""
from .. import symbol as sym


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")

    def block(src, num, filters, stage):
        body = src
        for i in range(num):
            body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                                   num_filter=filters,
                                   name=f"conv{stage}_{i + 1}")
            body = sym.Activation(body, act_type="relu",
                                  name=f"relu{stage}_{i + 1}")
        return sym.Pooling(body, pool_type="max", kernel=(2, 2), stride=(2, 2),
                           name=f"pool{stage}")

    body = block(data, 2, 64, 1)
    body = block(body, 2, 128, 2)
    body = block(body, 3, 256, 3)
    body = block(body, 3, 512, 4)
    body = block(body, 3, 512, 5)
    flatten = sym.Flatten(body, name="flatten")
    fc6 = sym.FullyConnected(flatten, num_hidden=4096, name="fc6")
    relu6 = sym.Activation(fc6, act_type="relu", name="relu6")
    drop6 = sym.Dropout(relu6, p=0.5, name="drop6")
    fc7 = sym.FullyConnected(drop6, num_hidden=4096, name="fc7")
    relu7 = sym.Activation(fc7, act_type="relu", name="relu7")
    drop7 = sym.Dropout(relu7, p=0.5, name="drop7")
    fc8 = sym.FullyConnected(drop7, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(fc8, name="softmax")
