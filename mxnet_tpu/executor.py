"""Graph executor.

Parity: src/executor/graph_executor.cc + python/mxnet/executor.py
(reference).  The reference compiles a Symbol into a static plan (gradient
graph, memory plan, cached engine ops — GraphExecutor::Init,
graph_executor.cc:316-351) and runs it by pushing ops to the dependency
engine.  TPU-natively the *whole plan is one XLA computation*:

- bind traces the graph into a pure function f(args, aux, key) ->
  (outputs, new_aux) and jits it — XLA buffer assignment replaces
  PlanMemory, XLA fusion replaces per-node kernels,
- the gradient graph (nnvm::pass::Gradient, graph_executor.cc:167-223) is
  jax.vjp over f, compiled together with the forward into one fused
  fwd+bwd executable — outputs and gradients materialize from a single
  device dispatch,
- forward(is_train=True) is *lazy*: it records inputs; if backward() is
  called before outputs are read, only the fused fwd+bwd computation runs
  (the reference gets the same effect from engine asynchrony: Python never
  blocks, SURVEY.md §3.1),
- grad_req write/add/null follow include/mxnet/op_attr_types.h OpReqType.

Executors created with ``shared_exec`` reuse the donor's compiled cache —
the TPU analogue of bucketing's shared memory pool
(GraphExecutor::Init(shared_exec), graph_executor.cc:330-334): what's
shared on TPU is compilation + params, while XLA reuses buffers per-call.
Beyond that object-identity path, a process-wide program cache keyed on
``Symbol.structural_signature()`` lets ANY bind of a structurally-equal
graph reuse the jitted executables (MXTPU_PROGRAM_CACHE, bounded LRU) —
repeated simple_bind, reshape, bucket regeneration, and serving rebinds
stop retracing/recompiling.
"""
from __future__ import annotations

import functools
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import ops
from . import telemetry as _tm
from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray
from .symbol import Symbol, _topo_order

_GRAD_REQ = ("write", "add", "null")

# --- telemetry families (zero-cost when disabled; docs/telemetry.md) -------
_TM_COMPILE = _tm.counter(
    "executor_compile_total",
    "graph traces handed to XLA: one per jit cache miss, including "
    "per-shape recompiles", labels=("kind",))
_TM_COMPILE_SEC = _tm.histogram(
    "executor_compile_seconds",
    "Python-trace portion of each XLA compile (seconds)", labels=("kind",))
_TM_GRAPH_CACHE = _tm.counter(
    "executor_graph_cache_total",
    "compiled graph-fn reuse: hit = shared_exec donor reused, miss = "
    "fresh jit built", labels=("result",))
_TM_FWD_SEC = _tm.histogram(
    "executor_forward_seconds",
    "Executor.forward wall time (dispatch; device-complete only under "
    "the profiler's sync mode)")
_TM_BWD_SEC = _tm.histogram(
    "executor_backward_seconds", "Executor.backward wall time (dispatch)")
_TM_COLLECTIVE = _tm.counter(
    "executor_collective_bytes_total",
    "logical payload bytes of mesh collectives the sharded paths "
    "request per dispatch (grad all-reduce, sharded-update param "
    "all-gather; estimate at dispatch, not wire bytes)", labels=("op",))


def _count_traces(fn, kind):
    """Wrap a to-be-jitted callable so each trace (= each XLA compile,
    including per-shape recompiles) increments the compile counter and
    times the Python-trace slice.  Runs at trace time only — compiled
    executions never reach this code."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        _TM_COMPILE.inc(kind=kind)
        t0 = time.perf_counter()
        res = fn(*args, **kwargs)
        _TM_COMPILE_SEC.observe(time.perf_counter() - t0, kind=kind)
        return res

    return wrapper


# ---------------------------------------------------------------------------
# Process-wide compiled-program cache.
#
# The reference amortizes graph setup with shared memory pools
# (GraphExecutor::Init(shared_exec)); on TPU the expensive artifact is the
# XLA executable, and the jit holding it was reachable only through
# object-identity ``shared_exec`` — BucketingModule regenerating a bucket
# symbol, executor_manager, Executor.reshape, and repeated simple_bind in
# tests/serving all retraced and recompiled structurally-identical graphs
# (the compile-amortization problem TVM/nGraph solve with artifact caches
# keyed on graph signature).  This cache keys the jitted fwd / fused
# fwd+bwd pair on Symbol.structural_signature() (+ platform + layout
# pass), so ANY bind of an equal-structure graph reuses the executables;
# jax.jit's own per-aval cache then handles shape/dtype variations under
# each entry.  Bounded LRU; MXTPU_PROGRAM_CACHE=0/off disables, =N sets
# capacity (docs/how_to/env_var.md).
# ---------------------------------------------------------------------------
_PROGRAM_CACHE_DEFAULT_CAPACITY = 64
_program_cache: "OrderedDict" = OrderedDict()
_program_cache_lock = threading.Lock()


def program_cache_capacity() -> int:
    """Resolved MXTPU_PROGRAM_CACHE capacity (0 = cache disabled)."""
    raw = os.environ.get("MXTPU_PROGRAM_CACHE", "").strip().lower()
    if raw in ("", "on", "true", "yes", "default"):
        return _PROGRAM_CACHE_DEFAULT_CAPACITY
    if raw in ("0", "off", "false", "no", "disable", "disabled"):
        return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        return _PROGRAM_CACHE_DEFAULT_CAPACITY


def program_cache_clear():
    """Drop every cached program (test isolation; frees held symbols)."""
    with _program_cache_lock:
        _program_cache.clear()


def program_cache_get(key):
    """Look up an entry by explicit key in the process-wide program LRU.

    Non-bind subsystems (the kvstore's bucketed fused-update engine)
    key their jitted programs into the same LRU so engine rebuilds,
    Module rebinds, and bucket-plan regeneration reuse executables; a
    hit counts in ``executor_graph_cache_total`` like a bind-time hit.
    Returns ``None`` when absent or when the cache is disabled (the
    caller builds and should then call :func:`program_cache_put`)."""
    if program_cache_capacity() <= 0:
        return None
    with _program_cache_lock:
        entry = _program_cache.get(key)
        if entry is not None:
            _program_cache.move_to_end(key)
    if entry is not None:
        _TM_GRAPH_CACHE.inc(result="hit")
    return entry


def program_cache_put(key, entry):
    """Insert an entry built after a :func:`program_cache_get` miss.

    Counts the miss and evicts least-recently-used entries past
    capacity; insertion is skipped (miss still counted) when the cache
    is disabled — the caller keeps its own reference either way."""
    _TM_GRAPH_CACHE.inc(result="miss")
    capacity = program_cache_capacity()
    if capacity <= 0:
        return
    with _program_cache_lock:
        _program_cache[key] = entry
        _program_cache.move_to_end(key)
        while len(_program_cache) > capacity:
            _program_cache.popitem(last=False)


def _compiled_programs(symbol: Symbol, platform: Optional[str],
                       shard_sig=None):
    """(graph_fn, jit_fwd, jit_fwdbwd) for a symbol, through the cache.

    Cache-key discipline: everything that changes the traced computation
    and is not already a jit cache axis must be in the key — the layout
    pass (channels_last) and the lowering platform are; grad reqs are not
    (they are static jit arguments of the fwdbwd program), and input
    avals are not (jax.jit keys on them per call).  ``shard_sig`` is the
    bind's mesh-sharding signature (executor `shardings` / group2ctx
    PartitionSpec placements): the traced Python is sharding-agnostic,
    but keying on it keeps a mesh-annotated bind's entry distinct from a
    single-device bind of the same structure, so cache hits always
    return programs whose jit-level sharding history matches the bind.

    The graph-rewrite pipeline (mxnet_tpu.passes; MXTPU_GRAPH_PASSES)
    runs FIRST, so the key is the POST-pass signature: differently-
    written but equivalent graphs — duplicated subexpressions, dead
    no-op nodes, unfused elementwise chains — rewrite to one canonical
    structure and converge on a single compiled entry.  Different pass
    selections need no extra key axis for the same reason: the
    rewritten structure IS the selection's fingerprint.

    The autotuner's schedule-cache fingerprint (mode + path + epoch) IS
    a key axis: tuned kernels (the residual epilogue's block_rows) bake
    their schedule in at trace time, so a program compiled before a
    search landed would silently keep the stale tiling — composing the
    fingerprint makes the next bind rebuild against the new winner.
    """
    from . import autotune as _autotune
    from . import passes as _passes

    symbol = _passes.apply_graph_passes(symbol)
    channels_last = channels_last_default()
    capacity = program_cache_capacity()
    key = None
    if capacity > 0:
        key = (symbol.structural_signature(), platform, channels_last,
               shard_sig, _autotune.fingerprint())
        with _program_cache_lock:
            entry = _program_cache.get(key)
            if entry is not None:
                _program_cache.move_to_end(key)
        if entry is not None:
            _TM_GRAPH_CACHE.inc(result="hit")
            return entry
    graph_fn = _build_graph_fn(symbol, channels_last=channels_last,
                               platform=platform)
    jit_fwd = jax.jit(_count_traces(graph_fn, "fwd"), static_argnums=(3,))
    jit_fwdbwd = jax.jit(
        _count_traces(_make_fwdbwd(graph_fn, placed=False), "fwdbwd"),
        static_argnames=("gnames", "add_names", "rs_specs"))
    entry = (graph_fn, jit_fwd, jit_fwdbwd)
    if key is not None:
        with _program_cache_lock:
            _program_cache[key] = entry
            _program_cache.move_to_end(key)
            while len(_program_cache) > capacity:
                _program_cache.popitem(last=False)
    _TM_GRAPH_CACHE.inc(result="miss")
    return entry


# ---------------------------------------------------------------------------
# Channels-last (NHWC) execution pass.
#
# The public API is NCHW (reference parity) but TPU compute wants the
# channel dim minor: XLA tiles the minor axis onto the 128-wide MXU/VPU
# lanes, and a logically-NCHW conv graph makes layout assignment insert
# transposes it cannot always elide (measured: ResNet-50 train step was
# HBM-bound at 14% MFU).  This pass keeps weights/params in their logical
# layouts and retraces the *activation* flow: 4D activations are
# transposed to NHWC once where they enter a spatial chain (normally the
# graph input) and back where they leave it (normally the global-pool /
# Flatten boundary); spatial ops run with __layout__="NHWC" (ops/nn.py),
# elementwise chains pass through untouched, and anything unknown falls
# back to NCHW — the pass can only change op *layouts*, never op math.
# Opt out with MXTPU_CONV_LAYOUT=NCHW.
# ---------------------------------------------------------------------------
_CL_SPATIAL = {"Convolution", "Pooling", "BatchNorm", "LRN"}
_CL_UNARY = {
    # single-tensor-input ops that commute with transpose
    "abs", "arccos", "arccosh", "arcsin", "arcsinh", "arctan", "arctanh",
    "ceil", "cos", "cosh", "degrees", "exp", "expm1", "fix", "floor",
    "gamma", "gammaln", "log", "log10", "log1p", "log2", "negative",
    "radians", "rint", "round", "rsqrt", "sign", "sin", "sinh", "sqrt",
    "square", "tan", "tanh", "sigmoid", "relu", "_copy", "identity",
    "BlockGrad", "stop_gradient", "Activation", "Dropout", "clip",
    "smooth_l1",
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_power_scalar", "_rpower_scalar",
    "_maximum_scalar", "_minimum_scalar", "_hypot_scalar",
    # a pre-fused elementwise chain (passes/prefuse.py) is itself a pure
    # elementwise map, so it passes NHWC through like its parts would
    "_fused_elemwise", "Cast",
}
_CL_MULTI = {
    # same-shape multi-tensor elementwise (incl. residual adds)
    "elemwise_add", "_plus", "_add", "_Plus", "elemwise_sub", "_minus",
    "_sub", "_Minus", "elemwise_mul", "_mul", "_Mul", "elemwise_div",
    "_div", "_Div", "_power", "_Power", "_maximum", "_Maximum",
    "_minimum", "_Minimum", "_hypot", "_grad_add",
    "ElementWiseSum", "add_n", "_sum",
}
_CL_CHANNEL_AXIS = {"Concat": "dim", "concat": "dim",
                    "SliceChannel": "axis", "split": "axis"}
# fused residual epilogues (ops/residual_epilogue.py): the two 4D
# activation inputs ride NHWC (that IS the Pallas kernel's layout);
# the per-channel affine/stat inputs stay logical 1-D
_CL_EPILOGUE = {"_residual_epilogue", "_residual_epilogue_bn"}


def channels_last_default() -> bool:
    return os.environ.get("MXTPU_CONV_LAYOUT", "NHWC").upper() != "NCHW"


def _to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def _to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def _cl_eligible(node, ins):
    """Can this spatial op run channels-last on these traced inputs?"""
    data = ins[0]
    if data.ndim != 4:
        return False
    if node.op == "Convolution":
        return len(ins) >= 2 and ins[1].ndim == 4  # 2D kernel only
    return True


def _cl_adapt(node, ins, lay, hwio_params=frozenset()):
    """Pick the execution layout for one node (trace time, zero runtime
    cost beyond the transposes actually emitted).  Returns
    (adapted_inputs, attrs, out_is_nhwc).

    ``hwio_params``: conv-weight variables whose STORAGE is physically
    HWIO (FusedTrainer keeps masters/momentum/compute-cache in the
    layout the NHWC conv consumes, so no per-step relayout traffic —
    measured +1.2 ms/step of 'data formatting' on ResNet-50 b32
    otherwise); the conv is told via __wlayout__ and reads it directly.
    """
    from .base import parse_attr, parse_bool

    name = node.op
    inlay = [lay.get((id(src), oidx), False) for src, oidx in node.inputs]
    attrs = node.attrs
    if name in _CL_SPATIAL and _cl_eligible(node, ins):
        data = ins[0] if inlay[0] else _to_nhwc(ins[0])
        # remaining inputs (weights/stats) must arrive in their logical
        # layouts — a computed weight coming off an NHWC activation chain
        # (dynamic-filter nets) is converted back
        rest = [(_to_nchw(x) if l else x)
                for x, l in zip(ins[1:], inlay[1:])]
        attrs = {**attrs, "__layout__": "NHWC"}
        if (name == "Convolution" and len(node.inputs) >= 2
                and node.inputs[1][0].is_variable
                and node.inputs[1][0].name in hwio_params):
            attrs["__wlayout__"] = "HWIO"
        return [data] + rest, attrs, True
    if name in _CL_EPILOGUE and len(ins) >= 2 and any(inlay[:2]) \
            and ins[0].ndim == 4 and ins[1].ndim == 4:
        a = ins[0] if inlay[0] else _to_nhwc(ins[0])
        b = ins[1] if inlay[1] else _to_nhwc(ins[1])
        rest = [(_to_nchw(x) if l else x)
                for x, l in zip(ins[2:], inlay[2:])]
        return [a, b] + rest, {**attrs, "__layout__": "NHWC"}, True
    if name in _CL_UNARY and len(ins) == 1 and inlay[0]:
        return ins, attrs, True
    if name in _CL_MULTI and any(inlay) and all(x.ndim == 4 for x in ins):
        return [x if l else _to_nhwc(x) for x, l in zip(ins, inlay)], attrs, True
    if name in _CL_CHANNEL_AXIS and any(inlay) and all(x.ndim == 4 for x in ins):
        axis_key = _CL_CHANNEL_AXIS[name]
        axis = int(parse_attr(attrs.get(axis_key, 1)))
        squeeze = (parse_bool(attrs.get("squeeze_axis", False))
                   if name in ("SliceChannel", "split") else False)
        if axis == 1 and not squeeze:
            ins = [x if l else _to_nhwc(x) for x, l in zip(ins, inlay)]
            return ins, {**attrs, axis_key: 3}, True
    # fallback: this op runs NCHW — convert whatever arrived channels-last
    return [(_to_nchw(x) if l else x) for x, l in zip(ins, inlay)], attrs, False


def _eval_node(node, topo_index, env, key, is_train, lay=None, platform=None,
               hwio_params=frozenset(), layout_report=None):
    """Evaluate one op node into env; returns {aux_name: new_val} updates.

    ``lay`` (entry -> is_nhwc) enables the channels-last pass; None keeps
    plain NCHW evaluation (the placed/segment path).  ``platform`` is the
    execution platform threaded into OpCtx (see registry.OpCtx).
    ``layout_report`` (a dict with "conv_w"/"other" sets) collects which
    variables are consumed as NHWC conv weights vs by anything else —
    the discovery pass behind FusedTrainer's HWIO weight storage (a
    variable is only HWIO-safe when NHWC convs are its ONLY consumers;
    any other reader would silently misinterpret the transposed axes).
    """
    od = ops.get(node.op)
    ins = [env[id(src)][oidx] for src, oidx in node.inputs]
    attrs = node.attrs
    out_nhwc = False
    if lay is not None:
        ins, attrs, out_nhwc = _cl_adapt(node, ins, lay, hwio_params)
        if layout_report is not None:
            for idx, (src, _oidx) in enumerate(node.inputs):
                if not src.is_variable:
                    continue
                if (node.op == "Convolution" and out_nhwc and idx == 1):
                    layout_report["conv_w"].add(src.name)
                else:
                    layout_report["other"].add(src.name)
    octx = ops.OpCtx(
        is_train=is_train,
        key=jax.random.fold_in(key, topo_index) if od.needs_rng else None,
        platform=platform,
    )
    res = od.fn(octx, *ins, **attrs)
    aux_updates = {}
    if od.aux_names:
        res, updates = res
        aux_arg_names = node.inputs[-len(od.aux_names):]
        for (aux_node, _), val in zip(aux_arg_names, updates):
            aux_updates[aux_node.name] = val
    if not isinstance(res, tuple):
        res = (res,)
    env[id(node)] = res
    if lay is not None:
        for k in range(len(res)):
            lay[(id(node), k)] = out_nhwc
    return aux_updates


def _build_graph_fn(symbol: Symbol, channels_last: Optional[bool] = None,
                    platform: Optional[str] = None,
                    hwio_params=frozenset(), layout_report=None):
    """Build f(arg_dict, aux_dict, key, is_train) -> (outputs, new_aux_dict).

    This is the tracing equivalent of GraphExecutor::InitCachedOps
    (graph_executor.cc:518-648): one closure per graph, evaluated under
    jax.jit so every node fuses into a single XLA program.  With
    ``channels_last`` (default from MXTPU_CONV_LAYOUT) 4D activation
    chains execute NHWC; graph outputs are always converted back to the
    logical NCHW layout.  ``platform`` tells platform-sensitive ops
    (FlashAttention: Pallas vs lax) what they will lower for; None means
    "the default backend".
    """
    if channels_last is None:
        channels_last = channels_last_default()
    out_entries = list(symbol._outputs)
    topo = _topo_order([n for n, _ in out_entries])
    # row-sparse-gradient Embedding nodes (sparse.rs_plan): evaluated
    # inline so (a) an optional zero "probe" rides on the gathered rows
    # — its vjp cotangent IS the per-row gradient, no dense scatter into
    # the table — and (b) the looked-up ids surface through new_aux for
    # the fwdbwd wrapper's in-trace unique-row segment-sum.  Probe-less
    # calls compute exactly what the Embedding op computes (clip + take),
    # so fwd-only paths and MXTPU_SPARSE_UPDATE=0 are bit-identical.
    from . import sparse as _sparse

    rs_nodes = {id(node): wname
                for wname, node in _sparse.rs_plan(symbol).items()}

    def fn(arg_vals: Dict, aux_vals: Dict, key, is_train: bool):
        env = {}
        lay = {} if channels_last else None
        new_aux = dict(aux_vals)
        for i, node in enumerate(topo):
            if node.is_variable:
                if node.is_aux:
                    env[id(node)] = (aux_vals[node.name],)
                else:
                    env[id(node)] = (arg_vals[node.name],)
                continue
            rsw = rs_nodes.get(id(node))
            if rsw is not None:
                data = env[id(node.inputs[0][0])][node.inputs[0][1]]
                w = env[id(node.inputs[1][0])][node.inputs[1][1]]
                if lay is not None:
                    if lay.get((id(node.inputs[0][0]), node.inputs[0][1])):
                        data = _to_nchw(data)
                    if lay.get((id(node.inputs[1][0]), node.inputs[1][1])):
                        w = _to_nchw(w)
                idx = jnp.clip(data.astype(jnp.int32), 0, w.shape[0] - 1)
                out = jnp.take(w, idx, axis=0)
                probe = arg_vals.get("__rs_probe__:" + rsw)
                if probe is not None:
                    out = out + probe.reshape(out.shape).astype(out.dtype)
                env[id(node)] = (out,)
                if lay is not None:
                    lay[(id(node), 0)] = False
                new_aux["__rs_idx__:" + rsw] = idx.reshape(-1)
                continue
            new_aux.update(_eval_node(node, i, env, key, is_train, lay,
                                      platform, hwio_params, layout_report))
        outputs = [
            _to_nchw(env[id(n)][i]) if lay and lay.get((id(n), i))
            else env[id(n)][i]
            for n, i in out_entries
        ]
        return outputs, new_aux

    return fn


# ---------------------------------------------------------------------------
# ctx_group placement (parity: nnvm::pass::PlaceDevice + _CrossDeviceCopy,
# graph_executor.cc:225-314)
# ---------------------------------------------------------------------------
def placement_plan(symbol: Symbol, group2ctx, default_ctx):
    """Assign every graph node a concrete jax.Device from its ctx_group.

    Returns (node_ctx, var_ctx, n_distinct) where node_ctx maps
    id(op_node) -> Context, var_ctx maps variable *name* -> Context (a
    variable lives with its first consumer, mirroring PlaceDevice's
    device propagation), and n_distinct counts distinct concrete devices
    in the plan.  group2ctx entries not matching any annotation are
    ignored, as in the reference (bind warns once per unknown group).
    """
    group2ctx = {g: c for g, c in group2ctx.items()
                 if isinstance(c, Context)}
    topo = _topo_order([n for n, _ in symbol._outputs])
    node_ctx, var_ctx = {}, {}
    # a variable's OWN annotation wins (reference PlaceDevice honors the
    # node's __ctx_group__); unannotated variables fall to first consumer
    for node in topo:
        if node.is_variable:
            grp = node.extra_attrs.get("ctx_group")
            if grp and grp in group2ctx:
                var_ctx[node.name] = group2ctx[grp]
    for node in topo:
        if node.is_variable:
            continue
        grp = node.extra_attrs.get("ctx_group")
        ctx = group2ctx.get(grp) if grp else None
        if ctx is None:
            ctx = default_ctx
        node_ctx[id(node)] = ctx
        for src, _ in node.inputs:
            if src.is_variable and src.name not in var_ctx:
                var_ctx[src.name] = ctx  # first consumer wins
    distinct = {c.jax_device for c in node_ctx.values()} | {
        c.jax_device for c in var_ctx.values()}
    return node_ctx, var_ctx, len(distinct)


# ---------------------------------------------------------------------------
# group2ctx -> mesh placement (the GSPMD half of PlaceDevice).
#
# A group2ctx value may be a jax.sharding.PartitionSpec (or a Sharding)
# instead of a Context: the group's variables are then placed as
# NamedSharding annotations on the process mesh
# (context.process_mesh(); MXTPU_MESH_SHAPE) and the whole graph stays
# ONE compiled SPMD program — XLA GSPMD inserts the collectives the
# reference's _CrossDeviceCopy edges would have been.  Contexts keep the
# segmented per-device plan for true disjoint-device model parallelism.
# ---------------------------------------------------------------------------
_warned_unknown_groups = set()


def _resolve_group_sharding(value):
    """group2ctx value -> NamedSharding on the process mesh, or None
    when the value is a Context (the segmented-placement path)."""
    from jax.sharding import PartitionSpec, Sharding

    if isinstance(value, Sharding):
        return value
    if isinstance(value, PartitionSpec):
        from .context import mesh_sharding

        return mesh_sharding(value)
    return None


def sharding_plan(symbol: Symbol, group2ctx):
    """{variable name: Sharding} for PartitionSpec-valued group2ctx
    entries, following placement_plan's propagation (a variable's own
    ctx_group wins; otherwise first consumer's group)."""
    spec_groups = {}
    for g, v in (group2ctx or {}).items():
        sh = _resolve_group_sharding(v)
        if sh is not None:
            spec_groups[g] = sh
    if not spec_groups:
        return {}
    topo = _topo_order([n for n, _ in symbol._outputs])
    var_sh = {}
    for node in topo:
        if node.is_variable:
            grp = node.extra_attrs.get("ctx_group")
            if grp in spec_groups:
                var_sh[node.name] = spec_groups[grp]
    for node in topo:
        if node.is_variable:
            continue
        grp = node.extra_attrs.get("ctx_group")
        sh = spec_groups.get(grp) if grp else None
        if sh is None:
            continue
        for src, _ in node.inputs:
            if src.is_variable and src.name not in var_sh:
                var_sh[src.name] = sh
    return var_sh


def _fit_sharding_rank(sh, ndim):
    """Adapt a NamedSharding to an array's rank: a group-level spec like
    P("model", None) also covers the group's rank-1 biases (Megatron
    convention: the bias shards with its weight's output dim) by
    truncating trailing spec entries the array has no dims for."""
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(sh, NamedSharding) or len(sh.spec) <= ndim:
        return sh
    return NamedSharding(sh.mesh, PartitionSpec(*sh.spec[:ndim]))


def _warn_unmatched_groups(symbol: Symbol, group2ctx):
    """A group2ctx entry naming a group no node is annotated with used
    to be silently ignored — a typo'd group name trained fully on the
    default device with nothing to say about it.  Warn once per name."""
    if not group2ctx:
        return
    annotated = {n.extra_attrs.get("ctx_group")
                 for n in symbol.nodes if n.extra_attrs.get("ctx_group")}
    for g in group2ctx:
        if g not in annotated and g not in _warned_unknown_groups:
            _warned_unknown_groups.add(g)
            import warnings

            warnings.warn(
                f"group2ctx group {g!r} matches no ctx_group annotation "
                f"in the symbol (annotated groups: {sorted(annotated)}); "
                "the entry is ignored", stacklevel=3)


class _Segment:
    """A maximal run of topo-consecutive op nodes on one device, compiled
    as one XLA program.  Transfers between segments are the explicit
    _CrossDeviceCopy points."""

    __slots__ = ("device", "nodes", "indices", "inputs", "outputs", "jit_fn")

    def __init__(self, device):
        self.device = device
        self.nodes = []
        self.indices = []  # global topo index per node (stable RNG folding)

    def finalize(self, produced_by_me, needed_entries):
        # entries this segment consumes but does not produce
        seen, ins = set(), []
        for node in self.nodes:
            for src, oidx in node.inputs:
                e = (id(src), oidx)
                if e not in produced_by_me and e not in seen:
                    seen.add(e)
                    ins.append(e)
        self.inputs = ins
        self.outputs = list(needed_entries)

        nodes, indices = self.nodes, self.indices
        inputs, outputs = self.inputs, self.outputs

        platform = getattr(self.device, "platform", None)

        def seg_fn(in_vals, key, is_train):
            env = {}
            for (nid, oidx), v in zip(inputs, in_vals):
                env.setdefault(nid, {})[oidx] = v
            aux_updates = {}
            for node, gi in zip(nodes, indices):
                aux_updates.update(_eval_node(node, gi, env, key, is_train,
                                              platform=platform))
            return tuple(env[nid][oidx] for nid, oidx in outputs), aux_updates

        self.jit_fn = jax.jit(_count_traces(seg_fn, "segment"),
                              static_argnums=(2,))


def _build_placed_fn(symbol: Symbol, node_ctx, var_ctx, default_ctx):
    """Multi-device execution plan for a ctx_group-annotated graph.

    The graph is cut into per-device segments; each segment is its own
    jit (committed to its device via its inputs), and jax.device_put
    between segments is the explicit transfer point — the TPU-native
    _CrossDeviceCopy.  XLA's async dispatch overlaps segments on
    different devices exactly the way the reference's dependency engine
    overlaps ctx_group stages (docs/how_to/model_parallel_lstm.md).
    Autodiff traces through the segment jits, so the fused fwd+bwd path
    and grad placement follow the same plan.
    """
    default_dev = default_ctx.jax_device
    node_device = {k: c.jax_device for k, c in node_ctx.items()}
    var_device = {k: c.jax_device for k, c in var_ctx.items()}
    out_entries = list(symbol._outputs)
    topo = _topo_order([n for n, _ in out_entries])

    segments = []
    node_seg = {}  # id(op_node) -> segment index
    for i, node in enumerate(topo):
        if node.is_variable:
            continue
        dev = node_device.get(id(node), default_dev)
        if not segments or segments[-1].device is not dev:
            segments.append(_Segment(dev))
        segments[-1].nodes.append(node)
        segments[-1].indices.append(i)
        node_seg[id(node)] = len(segments) - 1

    # entries needed outside their producing segment: graph outputs + any
    # entry crossing a segment boundary (those are the transfer points)
    needed = set((id(n), i) for n, i in out_entries if not n.is_variable)
    for si, seg in enumerate(segments):
        for node in seg.nodes:
            for src, oidx in node.inputs:
                if not src.is_variable and node_seg[id(src)] != si:
                    needed.add((id(src), oidx))
    for seg in segments:
        produced = set()
        for node in seg.nodes:
            for k in range(node.num_outputs()):
                produced.add((id(node), k))
        seg.finalize(produced, sorted(needed & produced))

    var_nodes = [n for n in topo if n.is_variable]

    def fn(arg_vals: Dict, aux_vals: Dict, key, is_train: bool):
        env = {}
        for n in var_nodes:
            val = aux_vals[n.name] if n.is_aux else arg_vals[n.name]
            dev = var_device.get(n.name, default_dev)
            env[id(n)] = (jax.device_put(val, dev),)
        new_aux = dict(aux_vals)
        for seg in segments:
            ins = tuple(jax.device_put(env[nid][oidx], seg.device)
                        for nid, oidx in seg.inputs)
            outs, aux_updates = seg.jit_fn(
                ins, jax.device_put(key, seg.device), is_train)
            for (nid, oidx), v in zip(seg.outputs, outs):
                env.setdefault(nid, {})[oidx] = v
            new_aux.update(aux_updates)
        outputs = [env[id(n)][i] for n, i in out_entries]
        return outputs, new_aux

    return fn


def _zero_cotangent(x):
    """Zero cotangent for an aux leaf: floats get zeros_like; integer/
    bool leaves (the row-sparse path's looked-up ids riding in new_aux)
    take jax's float0 convention — an int-dtyped zero would be rejected
    by the vjp."""
    if jnp.issubdtype(jnp.result_type(x), jnp.inexact):
        return jnp.zeros_like(x)
    return np.zeros(np.shape(x), jax.dtypes.float0)


def _make_fwdbwd(graph_fn, placed: bool):
    """Build the fused fwd+bwd evaluator over ``graph_fn``.

    ``gnames`` (args needing grads) and ``add_names`` (the grad_req="add"
    subset) are static arguments: every write/add/null combination lowers
    to its own fully-fused XLA program.  ``grad_ins`` carries the current
    grad buffers for ``add_names`` so accumulation happens INSIDE the
    compiled program (reference OpReqType kAddTo semantics,
    include/mxnet/op_attr_types.h) instead of an eager read-add-write
    round trip per param.  An empty ``head_grads`` means "seed with ones":
    the cotangents are built in-trace from the forward outputs — a
    loss-graph backward() therefore costs no per-call jax.eval_shape and
    no extra host dispatches for the seed arrays.

    ``rs_specs`` (static) lists the row-sparse-gradient embedding
    weights as ``(name, n_ids, row_dim, dtype)``: each gets an in-trace
    zero probe differentiated INSTEAD of the table itself, and its
    cotangent — the per-lookup gradient rows — is coalesced by the
    in-trace unique-row segment-sum into the ``(indices, values)`` pair
    returned as that weight's gradient.  The dense scatter into the
    full table never happens.

    ``loss_scale`` (None when AMP loss scaling is off — the off path
    traces bit-identically) is the scaler's DEVICE scalar: gradients
    are multiplied by it in-trace at the vjp boundary.  The boundary —
    not the ones seed — because the reference's loss-output ops
    (SoftmaxOutput & co.) discard the head cotangent by contract, so a
    seed-side scale would silently not propagate through the graphs
    the Module path actually trains.  The fused kvstore bucket update
    unscales (and detects overflow / skips) in ITS program; the scale
    is constant between optimizer steps, so grad_req="add"
    accumulation across backwards composes exactly.
    """

    def fwdbwd(arg_vals, aux_vals, key, head_grads, grad_ins, loss_scale,
               gnames: tuple, add_names: tuple, rs_specs: tuple = ()):
        def fwd_for_grad(grad_args):
            merged = dict(arg_vals)
            merged.update(grad_args)
            outs, new_aux = graph_fn(merged, aux_vals, key, True)
            return outs, new_aux

        grad_args = {k: arg_vals[k] for k in gnames}
        for wname, n_ids, row_dim, dt in rs_specs:
            # zero probe built in-trace (XLA folds it): the graph fn
            # adds it onto the gathered rows, so d out/d probe is the
            # row gradient — shape-stable at n_ids slots
            grad_args["__rs_probe__:" + wname] = jnp.zeros(
                (n_ids, row_dim), jnp.dtype(dt))
        (outs, new_aux), vjp_fn = jax.vjp(
            lambda ga: fwd_for_grad(ga), grad_args, has_aux=False
        )
        provided_heads = bool(head_grads)
        if not head_grads:
            # ones seed — custom_vjp loss ops discard it (parity with
            # reference loss-op backward semantics); placement follows
            # each output, so the placed path needs no device_put either
            head_grads = [jnp.ones_like(o) for o in outs]
        else:
            # caller-provided seeds follow the OUTPUT dtype (an
            # amp_cast-rewritten graph may emit bf16 outputs; an f32
            # ones seed would be rejected by the vjp)
            head_grads = [
                h.astype(o.dtype) if h.dtype != o.dtype else h
                for h, o in zip(head_grads, outs)
            ]
        if provided_heads and placed:
            # the seed cotangent must sit where its primal output sits,
            # or the last segment's transposed pjit sees mixed device
            # commitments; interior cotangents then follow the
            # transposed device_put edges automatically
            head_grads = [
                jax.device_put(h, next(iter(o.devices())))
                for h, o in zip(head_grads, outs)
            ]
        # cotangent: (outputs_cot, aux_cot=zeros; float0 for int leaves)
        aux_cot = jax.tree_util.tree_map(_zero_cotangent, new_aux)
        (grads,) = vjp_fn((list(head_grads), aux_cot))
        if rs_specs:
            from . import sparse as _sparse

            grads = dict(grads)
            for wname, n_ids, row_dim, dt in rs_specs:
                vals = grads.pop("__rs_probe__:" + wname)
                ids = new_aux["__rs_idx__:" + wname]
                sid, gvals, _first = _sparse.coalesce_rows(ids, vals)
                grads[wname] = (sid, gvals)
        if loss_scale is not None:
            grads = {
                k: ((g[0], g[1] * loss_scale.astype(g[1].dtype))
                    if isinstance(g, tuple)
                    else g * loss_scale.astype(g.dtype))
                for k, g in grads.items()
            }
        if add_names:
            grads = dict(grads)
            for k in add_names:
                # grad_in + g, matching the retired eager path's operand
                # order bit-for-bit
                grads[k] = grad_ins[k] + grads[k]
        return outs, new_aux, grads

    return fwdbwd


class Executor:
    """Parity: include/mxnet/executor.h Executor + python/mxnet/executor.py."""

    def _platform(self):
        """Platform of this executor's bind device, for OpCtx threading."""
        try:
            return self._ctx.jax_device.platform
        except Exception:  # noqa: BLE001 — unresolvable ctx: defer to default
            return None

    def __init__(self, symbol: Symbol, ctx: Optional[Context], args, args_grad,
                 grad_req="write", aux_states=None, group2ctx=None,
                 shared_exec: "Executor" = None, shardings=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self._group2ctx = group2ctx or {}
        _warn_unmatched_groups(symbol, self._group2ctx)
        # mesh-sharding annotations: explicit `shardings` ({var name ->
        # jax Sharding}, e.g. from DataParallelExecutorGroup) merged
        # over group2ctx PartitionSpec placements.  These place the
        # bound arrays; the jitted programs see the placements through
        # their committed inputs (GSPMD spans the mesh from them), and
        # the signature below keys the program cache.
        self._shardings = dict(sharding_plan(symbol, self._group2ctx))
        self._shardings.update(shardings or {})
        self._shard_sig = tuple(sorted(
            (name, str(sh)) for name, sh in self._shardings.items())) or None
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        # --- normalize arg containers (parity: executor bind signature) ----
        if isinstance(args, dict):
            self.arg_dict = {k: args[k] for k in arg_names if k in args}
            missing = [k for k in arg_names if k not in args]
            if missing:
                raise MXNetError(f"bind: missing arguments {missing}")
            self.arg_arrays = [self.arg_dict[k] for k in arg_names]
        else:
            args = list(args or [])
            if len(args) != len(arg_names):
                raise MXNetError(
                    f"bind: expected {len(arg_names)} args ({arg_names}), got {len(args)}"
                )
            self.arg_arrays = args
            self.arg_dict = dict(zip(arg_names, args))

        if isinstance(args_grad, dict):
            self.grad_dict = dict(args_grad)
        elif args_grad is None:
            self.grad_dict = {}
        else:
            self.grad_dict = dict(zip(arg_names, args_grad))
        self.grad_arrays = [self.grad_dict.get(k) for k in arg_names]

        if isinstance(grad_req, str):
            self.grad_req = {k: grad_req for k in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(arg_names, grad_req))
        else:
            self.grad_req = {k: grad_req.get(k, "null") for k in arg_names}
        for k, v in self.grad_req.items():
            if v not in _GRAD_REQ:
                raise MXNetError(f"invalid grad_req {v} for {k}")
        # args without a grad array can't be written
        for k in arg_names:
            if k not in self.grad_dict:
                self.grad_req[k] = "null"

        if isinstance(aux_states, dict):
            self.aux_dict = dict(aux_states)
        else:
            self.aux_dict = dict(zip(aux_names, aux_states or []))
        missing_aux = [k for k in aux_names if k not in self.aux_dict]
        if missing_aux:
            raise MXNetError(f"bind: missing aux states {missing_aux}")
        self.aux_arrays = [self.aux_dict[k] for k in aux_names]

        # place annotated arrays on their mesh shardings (one batched
        # transfer; arrays already carrying the target sharding pass).
        # Any mesh annotation commits the WHOLE bind to that mesh:
        # unannotated arrays default to replicated, or the jit would see
        # mixed single-device/mesh operands and refuse to compile.
        if self._shardings:
            from jax.sharding import NamedSharding, PartitionSpec

            meshes = [sh.mesh for sh in self._shardings.values()
                      if isinstance(sh, NamedSharding) and sh.mesh.size > 1]
            if meshes:
                repl = NamedSharding(meshes[0], PartitionSpec())
                for name in list(arg_names) + list(aux_names):
                    self._shardings.setdefault(name, repl)
            todo, targets = {}, {}
            for name, sh in self._shardings.items():
                for store in (self.arg_dict, self.aux_dict, self.grad_dict):
                    arr = store.get(name)
                    if arr is None or getattr(arr, "stype",
                                              "default") != "default":
                        # a row-sparse grad holder has no dense buffer
                        # to place; its (indices, values) land sharded
                        # by the backward program itself
                        continue
                    raw = arr._read()
                    tgt = _fit_sharding_rank(sh, raw.ndim)
                    if getattr(raw, "sharding", None) != tgt:
                        todo[id(arr)] = raw
                        targets[id(arr)] = (arr, tgt)
            if todo:
                moved = jax.device_put(
                    todo, {k: targets[k][1] for k in todo})
                for k, raw in moved.items():
                    targets[k][0]._chunk.write(raw)

        # ctx_group placement (parity: PlaceDevice, graph_executor.cc:225-314):
        # only a plan spanning >1 device changes execution; a single-device
        # plan keeps the whole-graph jit fast path.
        self._placed = False
        self._plan = None
        if self._group2ctx:
            node_dev, var_dev, n_distinct = placement_plan(
                symbol, self._group2ctx, self._ctx)
            self._placed = n_distinct > 1
            if self._placed:
                self._plan = (node_dev, var_dev)
        self._grad_names = [k for k in arg_names if self.grad_req.get(k) != "null"]
        # row-sparse gradient emission: args whose grad buffer is a
        # RowSparseNDArray holder (simple_bind allocates them for
        # grad_stype="row_sparse" variables when MXTPU_SPARSE_UPDATE is
        # on) leave the vjp'd name set and get probe specs instead
        rs_holders = sorted(
            k for k, g in self.grad_dict.items()
            if getattr(g, "stype", "default") == "row_sparse")
        self._rs_specs = self._build_rs_specs(symbol, rs_holders) \
            if rs_holders else ()
        rs_set = {s[0] for s in self._rs_specs}
        # static arguments of the fused fwd+bwd program: which args need
        # grads, and which of those accumulate (grad_req="add") INSIDE the
        # compiled program — fixed at bind time, so precomputed once
        self._gnames = tuple(k for k in self._grad_names if k not in rs_set)
        self._add_names = tuple(
            k for k in self._grad_names
            if self.grad_req.get(k) == "add" and k not in rs_set)
        if self._placed:
            self._graph_fn = _build_placed_fn(symbol, node_dev, var_dev, self._ctx)
            # segments carry their own jits; the outer pipeline must stay
            # un-jitted or GSPMD would re-place everything on one device —
            # and the program cache is skipped: the plan is keyed by
            # concrete devices, not graph structure
            self._jit_fwd = self._graph_fn
            self._jit_fwdbwd = _make_fwdbwd(self._graph_fn, placed=True)
            _TM_GRAPH_CACHE.inc(result="miss")
        elif shared_exec is not None and shared_exec._symbol is symbol:
            # object-identity fast path (no signature hash); the donor's
            # entry already sits in the program cache when it is enabled
            self._graph_fn = shared_exec._graph_fn
            self._jit_fwd = shared_exec._jit_fwd
            self._jit_fwdbwd = shared_exec._jit_fwdbwd
            _TM_GRAPH_CACHE.inc(result="hit")
        else:
            self._graph_fn, self._jit_fwd, self._jit_fwdbwd = \
                _compiled_programs(symbol, self._platform(),
                                   shard_sig=self._shard_sig)
        # AMP dynamic loss scaling is a BIND-TIME decision (docs/amp.md):
        # placed (ctx_group segmented) graphs skip the pass pipeline and
        # therefore the whole AMP policy
        from . import amp as _amp

        self._amp_scale = (not self._placed) and _amp.scaling_active()
        self._step = 0
        self._pending = None  # (args_raw, aux_raw, key) of last train forward
        self._outputs_cache: Optional[List] = None
        # per-step input-dict reuse (see _gather_inputs): {name: value}
        # dicts mutated in place + (ndarray, chunk, version) fingerprints
        self._args_cache = ({}, {})
        self._aux_cache = ({}, {})
        self._monitor_callback = None
        self._monitor_fn = None   # lazily-compiled internals tap
        self._monitor_names = None
        # device-memory accounting (telemetry/health.py): one
        # attribution row per bound program, keyed by structure so
        # rebinds refresh rather than multiply; shape math here, the
        # compiled memory_analysis upgrade happens at first forward on
        # non-CPU backends
        self._program_label = self._record_bind_memory()
        self._mem_analyzed = False
        # perf-attribution plane (telemetry/perf.py, MXTPU_PERF_ATTR):
        # one analytical cost row per compiled program at first
        # dispatch, fwd and fwdbwd each captured once (the fwdbwd row
        # wins the shared label once training runs); the train
        # forward's host wall is carried into backward's dispatch
        # record so the fused program owns the whole fwd+bwd wall
        self._cost_fwd_done = False
        self._cost_fwdbwd_done = False
        self._pending_fwd_wall = 0.0

    def _build_rs_specs(self, symbol, rs_holders):
        """Static ``(name, n_ids, row_dim, dtype)`` probe specs for the
        fused fwd+bwd program, one per row-sparse grad holder.  The id
        count comes from the Embedding node's data-input shape under the
        bound arg shapes, so the spec (and the compiled program) is
        fixed per bind like every other shape."""
        from . import sparse as _sparse

        if self._placed:
            raise MXNetError(
                "row_sparse gradients are not supported with ctx_group "
                "Context placement; use mesh PartitionSpec placement or "
                "dense gradients")
        plan = _sparse.rs_plan(symbol)
        known = {k: v.shape for k, v in self.arg_dict.items()}
        shapes, _ = symbol._infer(known, {}, partial=True)
        specs = []
        for wname in rs_holders:
            node = plan.get(wname)
            w_arr = self.arg_dict.get(wname)
            if node is None or w_arr is None \
                    or self.grad_req.get(wname) != "write":
                raise MXNetError(
                    f"bind: arg {wname!r} has a row_sparse gradient "
                    "buffer but is not the sole weight of one Embedding "
                    "op with grad_req='write'; bind a dense gradient "
                    "instead")
            src, oidx = node.inputs[0]
            dshape = shapes.get((src.name, "var")) if src.is_variable \
                else shapes.get((id(src), oidx))
            if dshape is None or len(w_arr.shape) != 2:
                raise MXNetError(
                    f"bind: cannot infer the lookup shape feeding "
                    f"Embedding weight {wname!r}")
            specs.append((wname, int(np.prod(dshape)),
                          int(w_arr.shape[1]),
                          np.dtype(w_arr.dtype).name))
        return tuple(specs)

    def _record_bind_memory(self):
        try:
            try:
                sig = str(self._symbol.structural_signature())[:10]
            except Exception:  # noqa: BLE001
                sig = "%x" % (id(self._symbol) & 0xFFFFFF)
            label = f"{self._symbol.name or 'graph'}[{sig}]"

            def _nd_bytes(nd_arr):
                return int(nd_arr.size) * np.dtype(nd_arr.dtype).itemsize

            arg_b = sum(_nd_bytes(v) for v in self.arg_dict.values())
            arg_b += sum(_nd_bytes(v) for v in self.aux_dict.values())
            grad_b = sum(_nd_bytes(v) for v in self.grad_dict.values()
                         if v is not None)
            out_b = 0
            try:
                shapes = {k: v.shape for k, v in self.arg_dict.items()}
                _, out_shapes, _ = self._symbol.infer_shape(**shapes)
                out_b = sum(int(np.prod(s)) * 4 for s in out_shapes or ())
            except Exception:  # noqa: BLE001 — unknown outputs stay 0
                pass
            _tm.health.record_program(label, argument=arg_b + grad_b,
                                      output=out_b, source="shape_math")
            return label
        except Exception:  # noqa: BLE001 — accounting must never break bind
            return self._symbol.name or "graph"

    # ---------------------------------------------------------------- running
    @staticmethod
    def _read_through_cache(nd_dict, cache):
        """Per-step input gather without rebuilding the dict.

        The {name: jax.Array} dict handed to the jit is held and mutated
        in place; an entry is re-read only when its NDArray object, chunk,
        or chunk version changed since the last step (optimizer writes
        bump the version; bind-time storage sharing swaps the object).  A
        pending host_waiter (async kvstore pull) always forces the read so
        deferred engine writes land before dispatch.
        """
        vals, fps = cache
        for k, v in nd_dict.items():
            ch = v._chunk
            fp = fps.get(k)
            if (fp is None or ch.host_waiter is not None or fp[0] is not v
                    or fp[1] is not ch or fp[2] != ch.version):
                vals[k] = v._read()
                ch = v._chunk
                fps[k] = (v, ch, ch.version)
        return vals

    def _gather_inputs(self):
        args = self._read_through_cache(self.arg_dict, self._args_cache)
        aux = self._read_through_cache(self.aux_dict, self._aux_cache)
        from . import random as _random

        key = jax.random.fold_in(_random.current_key(), self._step)
        self._step += 1
        return args, aux, key

    def forward(self, is_train=False, **kwargs):
        """Parity: Executor.forward (python/mxnet/executor.py:84 ->
        GraphExecutor::Forward)."""
        perf_on = _tm.perf.enabled()
        tp0 = time.perf_counter() if perf_on else 0.0
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"unknown input {k}")
            if isinstance(v, NDArray):
                self.arg_dict[k]._set(v._read())
            else:
                arr = np.asarray(v)
                if arr.dtype == np.float64:
                    # untyped Python floats arrive as f64; the framework
                    # default is f32.  Everything else (int labels, f16
                    # inputs, ...) keeps its dtype
                    arr = arr.astype(np.float32)
                self.arg_dict[k]._set(jnp.asarray(arr))
        args, aux, key = self._gather_inputs()
        if is_train:
            # lazy: defer compute so backward() can run the fused fwd+bwd
            self._pending = (args, aux, key)
            self._outputs_cache = None
            outs = self.outputs  # materializes via _jit_fwd (train mode)
            self._pending_fwd_wall = \
                (time.perf_counter() - tp0) if perf_on else 0.0
            return outs
        else:
            from . import profiler as _prof

            t0 = time.perf_counter() if _tm.enabled() else None
            with _prof.span(f"forward[{self._symbol.name or 'graph'}]",
                            device=str(self._ctx),
                            sync=lambda: jax.block_until_ready(
                                self._outputs_cache[0]._read())
                            if self._outputs_cache else None):
                try:
                    outs, new_aux = self._jit_fwd(args, aux, key, False)
                except Exception as e:  # noqa: BLE001 — OOM gets a report
                    _tm.health.reraise_if_oom(e, site="executor.forward")
                    raise
                self._pending = None
                self._outputs_cache = [NDArray(o) for o in outs]
                if not self._mem_analyzed:
                    # accelerator backends: upgrade the shape-math row
                    # with the compiled program's memory analysis (a
                    # cache lookup there; skipped entirely on CPU)
                    self._mem_analyzed = True
                    _tm.health.attach_compiled_analysis(
                        self._program_label, self._jit_fwd,
                        args, aux, key, False)
                if perf_on and not self._cost_fwd_done:
                    self._cost_fwd_done = True
                    _tm.perf.attach_cost_analysis(
                        self._program_label, self._jit_fwd,
                        args, aux, key, False)
            if t0 is not None:
                _TM_FWD_SEC.observe(time.perf_counter() - t0)
            if perf_on:
                _tm.perf.record_dispatch(self._program_label,
                                         time.perf_counter() - tp0)
            if self._monitor_callback is not None:
                self._run_monitor(args, aux, key)
        return self.outputs

    def backward(self, out_grads=None):
        """Parity: Executor.backward (executor.py:123 ->
        GraphExecutor::Backward); grads land in grad_arrays per grad_req."""
        if self._pending is None:
            raise MXNetError("backward() requires forward(is_train=True) first")
        from . import profiler as _prof

        perf_on = _tm.perf.enabled()
        t0 = time.perf_counter() if (_tm.enabled() or perf_on) else None
        with _prof.span(f"forward_backward[{self._symbol.name or 'graph'}]",
                        device=str(self._ctx),
                        sync=lambda: jax.block_until_ready(
                            self._outputs_cache[0]._read())
                        if self._outputs_cache else None):
            self._backward_impl(out_grads)
        if t0 is not None:
            _TM_BWD_SEC.observe(time.perf_counter() - t0)
            if perf_on:
                # the fused program owns the train forward's host wall
                # too — so the per-program ledger matches the wall a
                # caller timing fwd+bwd (bench _dispatch_micro) sees
                _tm.perf.record_dispatch(
                    self._program_label,
                    time.perf_counter() - t0 + self._pending_fwd_wall)
                self._pending_fwd_wall = 0.0

    def _backward_impl(self, out_grads):
        args, aux, key = self._pending
        from jax.sharding import NamedSharding, PartitionSpec, \
            SingleDeviceSharding

        ref = next(iter(args.values()), None)
        ref_sh = getattr(ref, "sharding", None)
        if out_grads is None:
            # loss-output graphs: ops define their own grads (custom_vjp)
            # and ignore the seed; plain graphs get an in-trace ones seed
            # (sum-of-outputs loss) — see _make_fwdbwd
            head = []
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            head = [g._read() if isinstance(g, NDArray) else jnp.asarray(g) for g in out_grads]
            # pin head grads to the executor's device (caller may have
            # created them on the default device); a mesh-sharded bind
            # replicates them over its mesh — a single-device committed
            # seed would otherwise refuse to enter the SPMD program
            if isinstance(ref_sh, SingleDeviceSharding):
                head = [
                    jax.device_put(h, ref_sh)
                    if getattr(h, "sharding", None) != ref_sh
                    else h
                    for h in head
                ]
            elif isinstance(ref_sh, NamedSharding) and ref_sh.mesh.size > 1:
                repl = NamedSharding(ref_sh.mesh, PartitionSpec())
                head = [
                    jax.device_put(h, repl)
                    if getattr(h, "sharding", None) is None
                    or h.sharding.device_set != ref_sh.device_set
                    else h
                    for h in head
                ]
        grad_ins = {k: self.grad_dict[k]._read() for k in self._add_names}
        loss_scale = None
        if self._amp_scale:
            from . import amp as _amp

            loss_scale = _amp.global_scaler().scale_raw()
            # the scaler's device scalar must share the bind's committed
            # placement (4 bytes; an async transfer only after the
            # scale-update program moved it)
            if isinstance(ref_sh, NamedSharding) and ref_sh.mesh.size > 1:
                repl = NamedSharding(ref_sh.mesh, PartitionSpec())
                if getattr(loss_scale, "sharding", None) != repl:
                    loss_scale = jax.device_put(loss_scale, repl)
            elif isinstance(ref_sh, SingleDeviceSharding) \
                    and getattr(loss_scale, "sharding", None) != ref_sh:
                loss_scale = jax.device_put(loss_scale, ref_sh)
        try:
            outs, new_aux, grads = self._jit_fwdbwd(
                args, aux, key, head, grad_ins, loss_scale,
                gnames=self._gnames, add_names=self._add_names,
                rs_specs=self._rs_specs
            )
        except Exception as e:  # noqa: BLE001 — OOM gets a report
            _tm.health.reraise_if_oom(e, site="executor.backward")
            raise
        if not self._cost_fwdbwd_done and _tm.perf.enabled():
            # one-time analytical cost row for the fused fwd+bwd
            # program — same label as the memory row; overwrites the
            # eval-forward row once training runs (the fwdbwd program
            # is the one the fit loops attribute wall to)
            self._cost_fwdbwd_done = True
            _tm.perf.attach_cost_analysis(
                self._program_label, self._jit_fwdbwd,
                args, aux, key, head, grad_ins, loss_scale,
                gnames=self._gnames, add_names=self._add_names,
                rs_specs=self._rs_specs)
        self._outputs_cache = [NDArray(o) for o in outs]
        self._write_aux(new_aux)
        for k, g in grads.items():
            req = self.grad_req.get(k, "null")
            tgt = self.grad_dict.get(k)
            if tgt is None or req == "null":
                continue
            if isinstance(g, tuple):
                # row-sparse emission: the coalesced (indices, values)
                # pair rebinds the holder's storage — no dense buffer
                tgt._set_rows(*g)
                continue
            # grad_req="add" was already accumulated inside the compiled
            # program (grad_ins); every req lands with a plain write
            tgt._set(g)
        if self._monitor_callback is not None:
            self._run_monitor(args, aux, key)

    def _write_aux(self, new_aux):
        for k, v in new_aux.items():
            if k in self.aux_dict:
                self.aux_dict[k]._set(v)

    @property
    def outputs(self) -> List[NDArray]:
        if self._outputs_cache is None:
            if self._pending is None:
                raise MXNetError("no forward has been run")
            args, aux, key = self._pending
            t0 = time.perf_counter() if _tm.enabled() else None
            try:
                outs, new_aux = self._jit_fwd(args, aux, key, True)
            except Exception as e:  # noqa: BLE001 — OOM gets a report
                _tm.health.reraise_if_oom(e, site="executor.outputs")
                raise
            if t0 is not None:
                _TM_FWD_SEC.observe(time.perf_counter() - t0)
            self._outputs_cache = [NDArray(o) for o in outs]
            self._write_aux(new_aux)
        return self._outputs_cache

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # ------------------------------------------------------------- monitoring
    def set_monitor_callback(self, callback):
        """Parity: GraphExecutor::SetMonitorCallback (graph_executor.cc:63) —
        taps every internal output (used by mx.mon.Monitor)."""
        self._monitor_callback = callback

    def _run_monitor(self, args, aux, key):
        # compiled ONCE and cached: the reference's monitor is a near-free
        # callback on already-computed outputs (executor.cc monitor), so
        # re-tracing the whole graph in eager python per monitored batch
        # (O(graph) interpreter overhead) is not acceptable here either
        if self._monitor_fn is None:
            internals = self._symbol.get_internals()
            if self._placed:
                # internals share the same node objects, so the stored plan
                # (keyed by id(node) / var name) places them identically —
                # a flat _build_graph_fn would feed ops mixed-device operands
                self._monitor_fn = _build_placed_fn(internals, *self._plan,
                                                    self._ctx)
            else:
                self._monitor_fn = jax.jit(_build_graph_fn(internals),
                                           static_argnums=(3,))
            self._monitor_names = internals.list_outputs()
        outs, _ = self._monitor_fn(args, aux, key, False)
        for name, val in zip(self._monitor_names, outs):
            self._monitor_callback(name, NDArray(val))

    # ------------------------------------------------------------------- misc
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k]._set(v._read())
            elif not allow_extra_params:
                raise MXNetError(f"unknown param {k}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k]._set(v._read())
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux {k}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Parity: Executor.reshape — rebind with new shapes; on TPU this is
        just a fresh simple_bind (jit handles per-shape compilation cache)."""
        shapes = {k: v.shape for k, v in self.arg_dict.items()}
        shapes.update(kwargs)
        # carry the bound dtypes over (type_dict is honored now), so a
        # reshaped executor keeps e.g. integer-label buffers integer
        types = {k: v.dtype for k, v in self.arg_dict.items()}
        types.update({k: v.dtype for k, v in self.aux_dict.items()})
        return simple_bind(self._symbol, self._ctx, grad_req=self.grad_req,
                           type_dict=types, group2ctx=self._group2ctx or None,
                           shared_exec=self, shardings=self._shardings or None,
                           **shapes)

    @property
    def symbol(self):
        return self._symbol


def simple_bind(symbol: Symbol, ctx=None, grad_req="write", type_dict=None,
                group2ctx=None, shared_exec=None, shardings=None,
                **kwargs) -> Executor:
    """Parity: Symbol.simple_bind (python/mxnet/symbol.py:726): infer
    shapes, allocate arrays (+grads per grad_req), bind.

    ``type_dict`` assigns per-name dtypes to args/aux (parity: the
    reference's simple_bind type inference); a ``Variable(dtype=...)``
    annotation is the per-symbol default, and anything undeclared
    allocates float32.  Grad arrays always match their arg's dtype.
    ``shardings`` ({var name -> jax Sharding}) places the named arrays
    on the process mesh at bind — the named-axis path a
    DataParallelExecutorGroup or a group2ctx PartitionSpec annotation
    resolves to.
    """
    ctx = ctx or current_context()
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
    if arg_shapes is None:
        raise MXNetError(f"simple_bind: cannot infer shapes from {kwargs}")
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    dtypes = {}
    for node in symbol.nodes:
        if node.is_variable and "__dtype__" in node.extra_attrs:
            dtypes[node.name] = node.extra_attrs["__dtype__"]
    dtypes.update(type_dict or {})

    def _dtype(name):
        return jnp.dtype(np.dtype(dtypes.get(name, np.float32)))

    # ctx_group-annotated graphs: allocate each variable on its group's
    # device so params/grads live where their layer computes
    var_ctx = {}
    if group2ctx:
        _, var_ctx, _ = placement_plan(symbol, group2ctx, ctx)
    args = {}
    for name, shape in zip(arg_names, arg_shapes):
        args[name] = NDArray(jnp.zeros(shape, dtype=_dtype(name)),
                             ctx=var_ctx.get(name, ctx))
    aux = {}
    for name, shape in zip(aux_names, aux_shapes):
        aux[name] = NDArray(jnp.zeros(shape, dtype=_dtype(name)),
                            ctx=var_ctx.get(name, ctx))

    if isinstance(grad_req, str):
        req = {k: grad_req for k in arg_names}
    elif isinstance(grad_req, (list, tuple)):
        req = dict(zip(arg_names, grad_req))
    else:
        req = {k: grad_req.get(k, "null") for k in arg_names}
    # grad_stype="row_sparse" variables (threaded through the symbol's
    # __grad_stype__ annotation) get a RowSparseNDArray holder instead
    # of a table-sized dense buffer — the backward rebinds it with the
    # coalesced (indices, values) pair each step.  MXTPU_SPARSE_UPDATE=0
    # keeps dense buffers (and thereby the dense scatter) bit-identically.
    from . import sparse as _sparse

    rs_grad_names = set()
    if _sparse.sparse_update_enabled() and _sparse.annotated_rs_names(symbol):
        rs_grad_names = {name for name in _sparse.rs_plan(symbol)
                         if req.get(name) == "write"}
    shape_of = dict(zip(arg_names, arg_shapes))
    grads = {}
    for k in arg_names:
        if req.get(k, "null") == "null":
            continue
        if k in rs_grad_names:
            grads[k] = _sparse.zeros("row_sparse", shape_of[k],
                                     ctx=var_ctx.get(k, ctx),
                                     dtype=_dtype(k))
        else:
            grads[k] = NDArray(jnp.zeros(shape_of[k], dtype=_dtype(k)),
                               ctx=var_ctx.get(k, ctx))
    return Executor(symbol, ctx, args, grads, req, aux, group2ctx=group2ctx,
                    shared_exec=shared_exec, shardings=shardings)
