"""Bucketed, jit-fused KVStore update engine.

The eager update path (kvstore.py push/pull loops + optimizer.py
per-key ``update()``) pays one Python round-trip, one device copy, one
reduction, and one updater dispatch **per parameter** per step — ~300
tiny dispatches for a 100-param net.  This engine restructures the
Module step's kvstore half the way arXiv:2004.13336 restructures the
weight update and TVM (arXiv:1802.04799) argues for operator fusion:

- registered keys are grouped into size-capped **flat buckets**
  (``MXTPU_KV_BUCKET_MB``, default ~4MB; stable key order,
  dtype-segregated — a param bigger than the cap gets its own bucket),
- each bucket's per-device gradient copies are reduced with **one
  compiled reduction per bucket** (flatten+concat per source device,
  one transfer per device to the bucket's least-loaded merge device,
  one flat add) instead of one reduction per key,
- the optimizer update for every key in the bucket runs inside a
  **single jitted program** — the multi-tensor rules from
  optim_rules.py (shared with FusedTrainer) tree-mapped over the
  bucket's slices; optimizer state lives in the same NDArrays the eager
  ``Updater`` owns but stays **device-resident** (placed once, never
  re-materialized through ``as_in_context`` per step),
- pull becomes a bucket-sliced broadcast: out arrays adopt the updated
  buffers by chunk rebind when they share the store's devices (zero
  dispatches), with an explicit device_put only across device sets.

Per-step lr (including Adam's host-side bias correction) enters the
program as a traced scalar, so lr schedules never retrace; everything
else (bucket layout, optimizer hyperparams, per-key wd) is static and
forms the program's key in the executor's process-wide LRU
(``program_cache_get/put``) — rebinds, plan rebuilds, and new engine
instances reuse the compiled programs, visible as
``executor_graph_cache_total`` hits.

Eager per-key behavior stays available via ``MXTPU_FUSED_UPDATE=0`` and
remains the fallback for ``dist_*`` stores, custom Python updaters,
optimizers without a fused rule (``Optimizer.fused_rule()`` is None),
and pushes the engine cannot bucket (unregistered keys, ragged
per-device copy lists).  Interleaving eager and fused steps is safe:
both paths share the ``Updater``'s state store and the kvstore's value
NDArrays.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import executor as _executor
from . import telemetry as _tm
from .ndarray import NDArray
from .optim_rules import _RULES, flat_rule

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_FUSED_SEC = _tm.histogram(
    "kvstore_fused_update_seconds",
    "wall time of one batched fused push (bucket reductions + jitted "
    "multi-tensor optimizer updates; dispatch, not device completion)",
    labels=("store",))
_TM_BUCKET_COUNT = _tm.gauge(
    "kvstore_bucket_count",
    "flat buckets in the current fused-update plan", labels=("store",))
_TM_BUCKET_BYTES = _tm.histogram(
    "kvstore_bucket_bytes",
    "bytes per flat bucket at plan build (dtype-segregated, capped by "
    "MXTPU_KV_BUCKET_MB)", labels=("store",),
    buckets=(1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20,
             1 << 22, 1 << 23, 1 << 24, 1 << 26))

_TM_SHARD_GATHER = _tm.histogram(
    "kvstore_shard_gather_seconds",
    "host time materializing sharded optimizer-state vectors back into "
    "per-key NDArrays (sync_shard_state: save/load, eager interleave, "
    "plan rebuild — never the per-step hot path)", labels=("store",))

_DEFAULT_BUCKET_MB = 4.0


def fused_update_enabled() -> bool:
    """MXTPU_FUSED_UPDATE gate (default on)."""
    from .base import parse_bool

    return parse_bool(os.environ.get("MXTPU_FUSED_UPDATE", "1"))


def shard_update_enabled() -> bool:
    """MXTPU_SHARD_UPDATE gate (default on).

    When a bucket's gradients arrive as ONE mesh-global array over a
    >1-device mesh, the bucket program shards the weight update across
    the mesh per arXiv:2004.13336: reduce-scatter the flat gradient,
    run the optimizer rule on each replica's 1/N slice against
    device-resident SHARDED flat optimizer state, and all-gather the
    fresh parameters in-trace — ~1/N update FLOPs and ~1/N
    optimizer-state bytes per replica.  ``0`` keeps the replicated
    per-key bucket programs (bit-identical to rounds 7-10).  Sampled at
    plan build: flipping it mid-run takes effect at the next key-set
    change (or a fresh engine)."""
    from .base import parse_bool

    return parse_bool(os.environ.get("MXTPU_SHARD_UPDATE", "1"))


def bucket_cap_bytes() -> int:
    """Resolved MXTPU_KV_BUCKET_MB cap in bytes (fractions allowed)."""
    raw = os.environ.get("MXTPU_KV_BUCKET_MB", "").strip()
    try:
        mb = float(raw) if raw else _DEFAULT_BUCKET_MB
    except ValueError:
        mb = _DEFAULT_BUCKET_MB
    return max(int(mb * (1 << 20)), 1)


def _lead_device(raw):
    """Deterministic representative device of a (possibly sharded) array."""
    return sorted(raw.sharding.device_set, key=lambda d: d.id)[0]


def _state_slots(state) -> Tuple[NDArray, ...]:
    """Updater state container -> the rule's tuple layout (None -> (),
    single NDArray -> 1 slot, tuple -> as-is)."""
    if state is None:
        return ()
    if isinstance(state, (tuple, list)):
        return tuple(state)
    return (state,)


def _make_bucket_program(rule_name, opt_params, shapes, sizes, wds,
                         sentinel=False, mp=False, wdtype=None,
                         scaling=False):
    """One jitted program for a bucket: flatten+concat each device's
    grads, ONE flat reduction across devices, then the per-key slices
    run the shared update rule — XLA fuses the whole chain.  ``lrs``
    are traced scalars; shapes/sizes/wds/hyperparams are static.

    With ``sentinel`` (MXTPU_SENTINEL) the program ALSO returns a
    per-key isfinite mask and the bucket's gradient-norm scalar —
    computed inside the already-jitted chain, returned as device
    futures the health layer syncs only at reporting boundaries.

    With ``mp`` (fp32 master weights, docs/amp.md) each key's state
    tuple carries the master as its LAST slot: the rule runs entirely
    in fp32 against the master, and the fresh ``wdtype`` parameter is
    cast INSIDE this same program — the bf16 weight is a cache of the
    master, never the accumulator.

    With ``scaling`` (AMP dynamic loss scaling) the program takes the
    scale as a traced scalar, detects overflow on the merged gradient
    (the PR-5 sentinel's isfinite shape), unscales, and SELECTS
    old-vs-new weights and state per the finite flag — the skip-step
    is a ``jnp.where`` lattice, and the flag rides out as one device
    scalar for the scale-update lattice (amp.LossScaler.end_step)."""
    init_state, update = _RULES[rule_name](dict(opt_params))
    del init_state  # states come pre-created through the Updater
    out_dt = jnp.dtype(wdtype) if wdtype is not None else None

    def bucket_step(dev_parts, weights, states, lrs, scale=None):
        flats = []
        for part in dev_parts:
            if isinstance(part, (tuple, list)):
                segs = [jnp.ravel(g) for g in part]
                flats.append(segs[0] if len(segs) == 1
                             else jnp.concatenate(segs))
            else:  # pre-concatenated on the source device
                flats.append(jnp.ravel(part))
        merged = flats[0]
        for f in flats[1:]:
            merged = merged + f
        fin = None
        if scaling:
            fin = jnp.isfinite(merged).all()
            merged = merged * (1.0 / scale).astype(merged.dtype)
        new_w, new_s = [], []
        fins = []
        off = 0
        for i, shape in enumerate(shapes):
            g = merged[off:off + sizes[i]].reshape(shape)
            off += sizes[i]
            if sentinel:
                fins.append(jnp.isfinite(g).all())
            # lrs is ONE stacked traced vector (not n scalar leaves —
            # pytree flattening cost scales with leaf count on every
            # dispatch); lrs[i] is the key's traced scalar lr
            if mp:
                master = states[i][-1]
                nm, ns = update(master, g.astype(jnp.float32),
                                tuple(states[i][:-1]), lrs[i], wds[i])
                nw = nm.astype(out_dt)
                ns = tuple(ns) + (nm,)
            else:
                nw, ns = update(weights[i], g, states[i], lrs[i], wds[i])
                ns = tuple(ns)
            if scaling:
                nw = jnp.where(fin, nw, weights[i])
                ns = tuple(jnp.where(fin, a, b)
                           for a, b in zip(ns, states[i]))
            new_w.append(nw)
            new_s.append(ns)
        outs = [tuple(new_w), tuple(new_s)]
        if sentinel:
            # per-key flags + the bucket's grad norm, packed into ONE
            # extra output leaf (norm rides as the last entry)
            fin_vec = jnp.stack(fins).astype(jnp.float32)
            gnorm = jnp.sqrt(
                jnp.sum(jnp.square(merged.astype(jnp.float32))))
            outs.append(jnp.concatenate([fin_vec, gnorm[None]]))
        if scaling:
            outs.append(fin)
        return tuple(outs)

    return jax.jit(_executor._count_traces(bucket_step, "kv_update"))


def _make_sharded_bucket_program(rule_name, opt_params, shapes, sizes, wds,
                                 wdtype, mesh, sentinel=False, mp=False,
                                 scaling=False):
    """One jitted program for a CROSS-REPLICA SHARDED bucket
    (arXiv:2004.13336): the flat gradient/weight/state vectors are
    constrained to ``P(mesh.axis_names)`` so GSPMD gives each replica a
    1/N slice (for an already-reduced replicated gradient this is the
    reduce-scatter fused into the producing program's all-reduce), the
    flat-vector optimizer rule (optim_rules.flat_rule — bit-compatible
    elementwise math, lr/wd as per-element vectors) updates the slice
    against SHARDED flat state that never leaves the program sharded,
    and the fresh parameters are all-gathered in-trace by a replicated
    constraint before slicing back to per-key shapes.  Everything static
    (shapes, wd, mesh) keys the program in the executor LRU; lr stays a
    traced vector so schedules never retrace.

    ``mp``: the shard_state's LAST flat vector is the fp32 MASTER
    (1/N master bytes per replica — the arXiv:2004.13336 saving now
    covers the masters too): the flat rule runs on the master slice in
    fp32, and the replicated all-gather moves the freshly-CAST
    ``wdtype`` vector — for bf16 params that also halves the
    all-gather payload.  ``scaling``: traced scale in, overflow
    detection + unscale + jnp.where skip lattice in-trace, finite flag
    out (docs/amp.md)."""
    nslots, update = flat_rule(rule_name, opt_params)
    total = int(sum(sizes))
    n = mesh.size
    padded = -(-total // n) * n
    shard = NamedSharding(mesh, P(mesh.axis_names))
    repl = NamedSharding(mesh, P())
    sizes_np = np.asarray(sizes, np.int64)
    # per-element wd, cast to the compute dtype exactly as the
    # weak-typed Python float in the per-key kernel would be (fp32 when
    # the update runs on fp32 masters); pad region is 0
    wd_el = np.zeros(padded, np.float32 if mp else np.dtype(wdtype))
    wd_el[:total] = np.repeat(np.asarray(wds, np.float64), sizes_np)
    csc = jax.lax.with_sharding_constraint
    out_dt = jnp.dtype(wdtype)

    def bucket_step(parts, w_raws, shard_state, lrs, scale=None):
        gflat = jnp.ravel(parts[0]) if len(parts) == 1 else \
            jnp.concatenate([jnp.ravel(p) for p in parts])
        fin = jnp.isfinite(gflat).all() if scaling else None
        gflat = jnp.pad(gflat, (0, padded - total))
        g = csc(gflat, shard)
        if scaling:
            g = g * (1.0 / scale).astype(g.dtype)
        lr_el = jnp.pad(jnp.repeat(lrs, sizes_np,
                                   total_repeat_length=total),
                        (0, padded - total))
        lr_el = csc(lr_el, shard)
        if mp:
            master = shard_state[-1]
            new_w, new_s = update(master, g.astype(jnp.float32),
                                  tuple(shard_state[:-1]), lr_el,
                                  jnp.asarray(wd_el))
            new_s = tuple(new_s) + (new_w,)
        else:
            wflat = jnp.ravel(w_raws[0]) if len(w_raws) == 1 else \
                jnp.concatenate([jnp.ravel(w) for w in w_raws])
            wflat = csc(jnp.pad(wflat, (0, padded - total)), shard)
            new_w, new_s = update(wflat, g, shard_state, lr_el,
                                  jnp.asarray(wd_el))
            new_s = tuple(new_s)
        if scaling:
            new_s = tuple(jnp.where(fin, a, b)
                          for a, b in zip(new_s, shard_state))
            if mp:
                new_w = new_s[-1]  # the selected master
            else:
                wflat_old = jnp.ravel(w_raws[0]) if len(w_raws) == 1 \
                    else jnp.concatenate([jnp.ravel(w) for w in w_raws])
                wflat_old = csc(jnp.pad(wflat_old, (0, padded - total)),
                                shard)
                new_w = jnp.where(fin, new_w, wflat_old)
        new_s = tuple(csc(s, shard) for s in new_s)
        out_flat = new_w.astype(out_dt) if mp else new_w
        full = csc(out_flat, repl)  # the in-trace param all-gather
        outs, off = [], 0
        for shape, size in zip(shapes, sizes):
            outs.append(full[off:off + size].reshape(shape))
            off += size
        ret = [tuple(outs), new_s]
        if sentinel:
            fins = jnp.stack([jnp.isfinite(p).all() for p in parts])
            gnorm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            ret.append(jnp.concatenate([fins.astype(jnp.float32),
                                        gnorm[None]]))
        if scaling:
            ret.append(fin)
        return tuple(ret)

    return jax.jit(_executor._count_traces(bucket_step, "kv_update"))


_concat_flat = None


def _concat(parts):
    """Jitted flatten+concat, run on the parts' (source) device."""
    global _concat_flat
    if _concat_flat is None:
        _concat_flat = jax.jit(_executor._count_traces(
            lambda ps: jnp.concatenate([jnp.ravel(p) for p in ps]),
            "kv_concat"))
    return _concat_flat(tuple(parts))


class _Bucket:
    __slots__ = ("dtype", "keys", "shapes", "sizes", "nbytes",
                 "target", "tset",
                 # cross-replica sharded update (arXiv:2004.13336)
                 "shard_n", "shard_mesh", "shard_sharding", "padded",
                 "offsets", "nslots", "wdtype", "shard_state", "shard_src",
                 "mp")

    def __init__(self, dtype):
        self.dtype = dtype
        self.keys: List = []
        self.shapes: List[Tuple[int, ...]] = []
        self.sizes: List[int] = []
        self.nbytes = 0
        self.target = None   # jax Sharding the bucket executes under
        self.tset = None     # its device_set (cheap placement guard)
        self.shard_n = 1          # >1: this bucket runs the sharded program
        self.shard_mesh = None
        self.shard_sharding = None
        self.padded = 0           # flat length padded to a shard_n multiple
        self.offsets: List[int] = []
        self.nslots = 0           # optimizer state slots (uniform per rule)
        self.wdtype = None        # the bucket's (uniform) weight dtype
        self.shard_state = None   # tuple of SHARDED flat state vectors
        self.shard_src = None     # per-key state fingerprints at ingest
        self.mp = False           # fp32 master weights (docs/amp.md)


class _SparseBucket:
    """One row-sparse key's touched-rows-only update unit (ISSUE-9
    tentpole).  Embedding tables are the big keys, so a sparse bucket
    is per-key: ONE jitted program gathers the pushed rows' weight and
    optimizer-state slices, applies the shared rule, and scatter-adds
    the masked delta — cost scales with rows *touched*, not table
    rows.  A mesh-sharded table (NamedSharding over >1 devices, e.g.
    P("model") from group2ctx) keeps its sharding: the program
    constrains its outputs back to the table's layout and GSPMD routes
    each row's gather/scatter to the shard owning it."""

    __slots__ = ("key", "shape", "gdtype", "target", "tset", "repl",
                 "out_sharding", "mesh_sig", "nparts", "nslots", "mp")

    def __init__(self, key, w_raw, nparts):
        self.key = key
        self.shape = tuple(w_raw.shape)
        self.gdtype = np.dtype(w_raw.dtype)
        self.target = w_raw.sharding
        self.tset = self.target.device_set
        self.repl = None
        self.out_sharding = None
        self.mesh_sig = None
        self.nparts = nparts
        self.nslots = 0
        self.mp = False   # fp32 master rows for a low-precision table
        if isinstance(self.target, NamedSharding) \
                and self.target.mesh.size > 1:
            mesh = self.target.mesh
            # pushed (idx, vals) pairs enter replicated; the table and
            # state keep their own (possibly "model"-sharded) layout
            self.repl = NamedSharding(mesh, P())
            self.out_sharding = self.target
            self.mesh_sig = (mesh.axis_names, mesh.devices.shape,
                             tuple(d.id for d in mesh.devices.flat),
                             str(self.target.spec))


class FusedUpdateEngine:
    """Drives the bucketed fused update for one KVStore instance.

    Created by ``KVStore.set_optimizer`` when the optimizer exposes a
    fused rule; ``handle_push``/``handle_pull`` return False when a call
    is not bucketable so the store falls back to the eager loops."""

    def __init__(self, kvstore, optimizer, updater):
        self._kv = kvstore
        self._opt = optimizer
        self._updater = updater
        self._buckets: Optional[List[_Bucket]] = None
        self._sparse_buckets: List[_SparseBucket] = []
        self._plan_stypes: Optional[Tuple] = None
        self._plan_keys: Optional[Tuple] = None
        self._key_index: Dict = {}
        self._ndev = 0
        self._load: Dict = {}       # merge-device -> assigned bucket bytes
        self._local_programs: Dict = {}  # fallback when the LRU is off
        self._push_count = 0        # the sentinel's step id for this store
        self._cost_done: set = set()  # perf plane: buckets with cost rows

    @property
    def num_buckets(self) -> int:
        return len(self._buckets or ())

    # ----------------------------------------------------------------- plan
    def _build_plan(self, keys, vlists, ndev):
        cap = bucket_cap_bytes()
        buckets: List[_Bucket] = []
        sparse_buckets: List[_SparseBucket] = []
        cur = None
        for i, _k in enumerate(keys):
            if getattr(vlists[i][0], "stype", "default") == "row_sparse":
                # row-sparse keys get their own per-key touched-rows
                # bucket, executing where the stored table lives (its
                # sharding included — a "model"-sharded table stays
                # sharded through the update)
                w_raw = self._kv._store[keys[i]]._read()
                sb = _SparseBucket(keys[i], w_raw, ndev)
                from . import amp as _amp

                sb.mp = _amp.master_weights_wanted(self._opt, sb.gdtype)
                if _amp.is_low_precision(sb.gdtype) and not sb.mp:
                    _amp.warn_no_master(self._key_name(keys[i]))
                sparse_buckets.append(sb)
                continue
            g0 = vlists[i][0]._read()
            dt = np.dtype(g0.dtype)
            size = int(g0.size)
            nbytes = size * dt.itemsize
            if (cur is None or cur.dtype != dt
                    or (cur.nbytes and cur.nbytes + nbytes > cap)):
                cur = _Bucket(dt)
                buckets.append(cur)
            cur.keys.append(keys[i])
            cur.shapes.append(tuple(g0.shape))
            cur.sizes.append(size)
            cur.nbytes += nbytes
        self._sparse_buckets = sparse_buckets
        for si, sb in enumerate(sparse_buckets):
            state_b = int(np.prod(sb.shape)) * sb.gdtype.itemsize \
                * max(sb.nslots, 1)
            _tm.health.record_program(
                f"kv_sparse[{sb.key}:{'x'.join(map(str, sb.shape))}]",
                argument=state_b, output=state_b, source="shape_math")
        idx = {k: i for i, k in enumerate(keys)}
        from . import amp as _amp

        for b in buckets:
            # fp32-master decision is bucket-wide: keys are
            # dtype-segregated by GRAD dtype, so also require one
            # uniform WEIGHT dtype before turning masters on
            wdts = {np.dtype(self._kv._store[k].dtype) for k in b.keys
                    if k in self._kv._store}
            if len(wdts) == 1:
                b.wdtype = wdts.pop()
                b.mp = _amp.master_weights_wanted(self._opt, b.wdtype)
                if _amp.is_low_precision(b.wdtype) and not b.mp:
                    for k in b.keys:
                        _amp.warn_no_master(self._key_name(k))
            raws = [vlists[idx[b.keys[0]]][d]._read() for d in range(ndev)]
            if ndev == 1:
                # single (possibly mesh-global) grad per key: execute
                # where the gradients already live — zero grad transfers
                b.target = raws[0].sharding
            else:
                # per-device copies: least-loaded merge device among the
                # copies' devices, per bucket (parity: CommDevice::
                # InitMergeBuffer load balancing, comm.h:321-348, lifted
                # from per-key to per-bucket granularity)
                cands = sorted({_lead_device(r) for r in raws},
                               key=lambda d: (d.platform, d.id))
                dev = min(cands, key=lambda d: self._load.get(d, 0))
                self._load[dev] = self._load.get(dev, 0) + b.nbytes
                b.target = jax.sharding.SingleDeviceSharding(dev)
            b.tset = b.target.device_set
            self._maybe_shard_bucket(b, raws[0] if ndev == 1 else None)
            if _tm.enabled():
                _TM_BUCKET_BYTES.observe(b.nbytes, store=self._kv.type)
        for i, b in enumerate(buckets):
            # memory attribution row per bucket program: ndev grad
            # copies + weights in, weights (+ state, roughly weight-
            # sized per slot) out — shape math, good enough to RANK
            # programs in the OOM report.  A sharded bucket's state
            # (and its update temp) is resident at 1/N per replica —
            # the row is where the arXiv:2004.13336 memory saving shows
            # up in the health layer's accounting
            # mp adds the fp32 master as one more (weight-sized) state
            # slot; sharded buckets hold it at 1/N per replica — the
            # row is where the master-residency saving shows up
            slots = b.nslots + (1 if b.mp else 0)
            state_b = b.nbytes * max(slots, 1) // b.shard_n
            _tm.health.record_program(
                f"kv_bucket{i}[{np.dtype(b.dtype).name}x{len(b.keys)}"
                + (f"/shard{b.shard_n}" if b.shard_n > 1 else "")
                + ("/mp" if b.mp else "") + "]",
                argument=b.nbytes * (ndev + 1) + state_b,
                output=b.nbytes + state_b,
                temp=b.nbytes // b.shard_n, source="shape_math")
        self._buckets = buckets
        self._plan_keys = tuple(keys)
        self._key_index = idx
        self._ndev = ndev
        # perf plane: cost rows re-attach once per (re)plan
        self._cost_done = set()
        if _tm.enabled():
            _TM_BUCKET_COUNT.set(len(buckets), store=self._kv.type)

    def _maybe_shard_bucket(self, b, raw0):
        """Mark a bucket for the cross-replica sharded update when its
        (single, mesh-global) gradient is replicated over a >1-device
        mesh, the optimizer rule has a flat-vector form, and the
        bucket's weights share one dtype.  Per-device grad-copy lists
        (ndev > 1) and TP-sharded gradients keep the replicated
        per-key program."""
        b.offsets = [int(o) for o in np.cumsum([0] + b.sizes)[:-1]]
        if raw0 is None or not shard_update_enabled():
            return
        sh = raw0.sharding
        if not isinstance(sh, NamedSharding) or sh.mesh.size <= 1 \
                or not sh.is_fully_replicated:
            return
        rule = self._opt.fused_rule()
        flat = flat_rule(*rule) if rule is not None else None
        if flat is None:
            return
        if b.wdtype is None:  # mixed weight dtypes (set in _build_plan)
            return
        b.nslots = flat[0]
        b.shard_n = int(sh.mesh.size)
        b.shard_mesh = sh.mesh
        b.shard_sharding = NamedSharding(sh.mesh, P(sh.mesh.axis_names))
        total = int(sum(b.sizes))
        b.padded = -(-total // b.shard_n) * b.shard_n

    # ----------------------------------------------------------------- push
    def handle_push(self, keys, values) -> bool:
        """Run the fused bucketed update for one batched push; False if
        this call is not bucketable (caller falls back to eager)."""
        kv = self._kv
        vlists = [list(v) if isinstance(v, (list, tuple)) else [v]
                  for v in values]
        if not vlists:
            return False
        ndev = len(vlists[0])
        if ndev == 0:
            return False
        for k, vl in zip(keys, vlists):
            if k not in kv._store or len(vl) != ndev:
                return False
        t0 = time.perf_counter() if _tm.enabled() else None
        stypes = tuple(getattr(vl[0], "stype", "default")
                       for vl in vlists)
        if self._plan_keys != tuple(keys) or self._ndev != ndev \
                or self._plan_stypes != stypes:
            # a plan rebuild drops the old buckets: any sharded state
            # they hold must land back in the per-key NDArrays first
            self.sync_shard_state()
            self._build_plan(keys, vlists, ndev)
            self._plan_stypes = stypes
        opt = self._opt
        # host bookkeeping first (eager order: every key of the step sees
        # the same num_update), then the per-key traced lr / static wd
        for k in keys:
            opt._update_count(k)
        lrs = {k: float(opt.fused_lr(k)) for k in keys}
        wds = {k: float(opt._get_wd(k)) for k in keys}
        rule_name, opt_params = opt.fused_rule()
        self._push_count += 1
        # AMP dynamic loss scaling: the scale enters every bucket
        # program as a traced device scalar; each program returns a
        # finite flag, and ONE jitted lattice folds the step's flags
        # into the scale schedule — all device-side, zero host syncs
        from . import amp as _amp

        scaling = _amp.scaling_active()
        scale_raw = _amp.global_scaler().scale_raw() if scaling else None
        flags: List = []
        try:
            for bi, b in enumerate(self._buckets):
                flag = self._step_bucket(b, bi, vlists, rule_name,
                                         opt_params, lrs, wds, scale_raw)
                if flag is not None:
                    flags.append(flag)
            if self._sparse_buckets:
                ts = time.perf_counter() if t0 is not None else None
                for si, sb in enumerate(self._sparse_buckets):
                    flag = self._step_sparse_bucket(
                        sb, si, vlists, rule_name, opt_params, lrs, wds,
                        scale_raw)
                    if flag is not None:
                        flags.append(flag)
                if ts is not None:
                    from .sparse import _TM_SPARSE_SEC

                    _TM_SPARSE_SEC.observe(time.perf_counter() - ts,
                                           store=kv.type)
            if flags:
                _amp.global_scaler().end_step(flags)
        except Exception as e:  # noqa: BLE001 — OOM gets a report
            _tm.health.reraise_if_oom(e, site="kvstore_fused.push")
            raise
        if t0 is not None:
            _TM_FUSED_SEC.observe(time.perf_counter() - t0,
                                  store=kv.type)
        return True

    def _key_name(self, k):
        """Kvstore key -> the human name the sentinel reports (the
        optimizer's param_idx2name mapping when keys are indices)."""
        if isinstance(k, str):
            return k
        name = getattr(self._opt, "idx2name", {}).get(k)
        return name if name else str(k)

    def _place(self, nd_arr, target, tset):
        """Device-resident guard: returns the raw array, migrating the
        NDArray's chunk to the bucket's placement if (and only if) its
        device set differs — a metadata compare per step, a transfer
        only on the first fused step or after an eager interlude."""
        raw = nd_arr._read()
        if raw.sharding.device_set != tset:
            raw = jax.device_put(raw, target)
            nd_arr._chunk.write(raw)
        return raw

    def _step_bucket(self, b, bi, vlists, rule_name, opt_params, lrs, wds,
                     scale_raw=None):
        kv, upd = self._kv, self._updater
        sentinel = _tm.health.sentinel_mode() is not None
        scaling = scale_raw is not None
        weights = [kv._store[k] for k in b.keys]
        if b.shard_n > 1:
            return self._step_bucket_sharded(b, bi, vlists, rule_name,
                                             opt_params, lrs, wds,
                                             weights, sentinel, scale_raw)
        slot_lists = [
            _state_slots(upd.ensure_state(k, w))
            for k, w in zip(b.keys, weights)
        ]
        w_raws = [self._place(w, b.target, b.tset) for w in weights]
        s_raws = [tuple(self._place(s, b.target, b.tset) for s in slots)
                  for slots in slot_lists]
        idx = self._key_index
        if self._ndev == 1:
            parts = []
            for k in b.keys:
                g = vlists[idx[k]][0]._read()
                if g.sharding.device_set != b.tset:
                    g = jax.device_put(g, b.target)
                parts.append(g)
            dev_inputs = (tuple(parts),)
        else:
            flats = []
            for d in range(self._ndev):
                segs = [vlists[idx[k]][d]._read() for k in b.keys]
                # flatten+concat ON the source device, then ONE transfer
                # per device per bucket to the merge device
                flat = jnp.ravel(segs[0]) if len(segs) == 1 \
                    else _concat(segs)
                if flat.sharding.device_set != b.tset:
                    flat = jax.device_put(flat, b.target)
                flats.append(flat)
            dev_inputs = tuple(flats)
        wd_tuple = tuple(wds[k] for k in b.keys)
        fn = self._program(b, rule_name, opt_params, wd_tuple, sentinel,
                           scaling)
        lr_vec = np.asarray([lrs[k] for k in b.keys], np.float32)
        args = (dev_inputs, tuple(w_raws), tuple(s_raws), lr_vec)
        if scaling:
            sh = getattr(scale_raw, "sharding", None)
            if sh is not None and sh.device_set != b.tset:
                scale_raw = jax.device_put(scale_raw, b.target)
            args = args + (scale_raw,)
        res = fn(*args)
        if bi not in self._cost_done and _tm.perf.enabled():
            # perf plane: one analytical cost row per bucket program,
            # same label as the plan-time memory row (once per plan)
            self._cost_done.add(bi)
            _tm.perf.attach_cost_analysis(
                f"kv_bucket{bi}[{np.dtype(b.dtype).name}x{len(b.keys)}"
                + (f"/shard{b.shard_n}" if b.shard_n > 1 else "")
                + ("/mp" if b.mp else "") + "]",
                fn, *args)
        new_w, new_s = res[0], res[1]
        flag = res[-1] if scaling else None
        if sentinel:
            # park the device future — NO sync here; sentinel_check
            # reads it at the next reporting boundary
            _tm.health.sentinel_record(
                site=f"kv_bucket{bi}", step=self._push_count,
                names=[self._key_name(k) for k in b.keys],
                finite=res[2], packed_norm=True)
        for i, w in enumerate(weights):
            # outputs carry the bucket's placement by construction:
            # rebind the chunks directly (NDArray._set would device_put
            # back to the pre-migration sharding)
            w._chunk.write(new_w[i])
            for s_nd, s_raw in zip(slot_lists[i], new_s[i]):
                s_nd._chunk.write(s_raw)
        if _tm.enabled():
            from .kvstore import _TM_PUSH, _TM_PUSH_BYTES

            _TM_PUSH.inc(len(b.keys), store=kv.type)
            _TM_PUSH_BYTES.inc(b.nbytes, store=kv.type)
        return flag

    # --------------------------------------------------- sparse bucket step
    def _step_sparse_bucket(self, sb, si, vlists, rule_name, opt_params,
                            lrs, wds, scale_raw=None):
        """One touched-rows-only update: per-device (idx, vals) pairs in,
        ONE jitted program (concat → in-trace segment-sum coalesce →
        gather touched weight/state rows → shared rule → scatter-add
        masked delta) out.  No host syncs: the row count is host-known
        (it is the pushed slot count), lr is the traced scalar.  A bf16
        table under AMP carries an fp32 MASTER table as the last state
        slot: touched master rows update in fp32 and the bf16 rows are
        re-cast in the same program (lazy rows stay byte-identical in
        both)."""
        from . import sparse as _sparse

        kv, upd = self._kv, self._updater
        sentinel = _tm.health.sentinel_mode() is not None
        scaling = scale_raw is not None
        w = kv._store[sb.key]
        slots = _state_slots(upd.ensure_state(sb.key, w))
        sb.nslots = len(slots)
        w_raw = self._place(w, sb.target, sb.tset)
        s_raws = tuple(self._place(s, sb.target, sb.tset) for s in slots)
        idx_parts, val_parts = [], []
        nrows = 0
        for v in vlists[self._key_index[sb.key]]:
            ir = v.indices._read()
            vr = v.data._read()
            nrows += int(ir.shape[0])
            if ir.sharding.device_set != sb.tset:
                place = sb.repl if sb.repl is not None else sb.target
                ir = jax.device_put(ir, place)
                vr = jax.device_put(vr, place)
            idx_parts.append(ir)
            val_parts.append(vr)
        fn = self._sparse_program(sb, rule_name, opt_params,
                                  wds[sb.key], sentinel, scaling)
        lr = np.float32(lrs[sb.key])
        args = (tuple(idx_parts), tuple(val_parts), w_raw, s_raws, lr)
        if scaling:
            sc = scale_raw
            sh = getattr(sc, "sharding", None)
            if sh is not None and sh.device_set != sb.tset:
                sc = jax.device_put(
                    sc, sb.repl if sb.repl is not None else sb.target)
            args = args + (sc,)
        res = fn(*args)
        new_w, new_s = res[0], res[1]
        flag = res[-1] if scaling else None
        if sentinel:
            _tm.health.sentinel_record(
                site=f"kv_sparse{si}", step=self._push_count,
                names=[self._key_name(sb.key)], finite=res[2],
                packed_norm=True)
        w._chunk.write(new_w)
        for s_nd, s_raw in zip(slots, new_s):
            s_nd._chunk.write(s_raw)
        if _tm.enabled():
            from .kvstore import _TM_PUSH, _TM_PUSH_BYTES

            _TM_PUSH.inc(store=kv.type)
            row_b = nrows * (int(np.prod(sb.shape[1:])) + 1) \
                * sb.gdtype.itemsize
            _TM_PUSH_BYTES.inc(row_b, store=kv.type)
            _sparse._TM_SPARSE_ROWS.inc(nrows, store=kv.type)
            _sparse._TM_SPARSE_DENSITY.observe(
                nrows / max(sb.shape[0], 1), store=kv.type)
        return flag

    def _sparse_program(self, sb, rule_name, opt_params, wd_mult,
                        sentinel=False, scaling=False):
        key = ("kvsparse", rule_name, tuple(sorted(opt_params.items())),
               float(wd_mult), sb.gdtype.str, len(sb.shape), sb.nparts,
               sb.mesh_sig, sentinel)
        if sb.mp or scaling:
            key = key + (("amp", sb.mp, scaling),)
        fn = _executor.program_cache_get(key)
        if fn is None:
            fn = self._local_programs.get(key)
            if fn is None:
                from . import sparse as _sparse

                fn = _sparse.make_row_program(
                    rule_name, tuple(sorted(opt_params.items())),
                    float(wd_mult), sb.nparts, sentinel=sentinel,
                    out_sharding=sb.out_sharding, mp=sb.mp,
                    scaling=scaling)
                _executor.program_cache_put(key, fn)
        self._local_programs[key] = fn
        return fn

    # ------------------------------------------- cross-replica sharded step
    def _step_bucket_sharded(self, b, bi, vlists, rule_name, opt_params,
                             lrs, wds, weights, sentinel, scale_raw=None):
        """One sharded bucket step (arXiv:2004.13336): grads/weights
        enter per-key (replicated), the jitted program reduce-scatters
        the flat gradient, updates each replica's 1/N slice against the
        bucket's device-resident SHARDED flat state (fp32 masters
        included under AMP — 1/N master bytes per replica), and
        all-gathers fresh per-key weights — one compiled program, no
        host sync, no per-key state dispatches."""
        kv = self._kv
        scaling = scale_raw is not None
        self._ensure_shard_state(b)
        idx = self._key_index
        parts = []
        for k in b.keys:
            g = vlists[idx[k]][0]._read()
            if g.sharding.device_set != b.tset:
                g = jax.device_put(g, b.target)
            parts.append(g)
        w_raws = [self._place(w, b.target, b.tset) for w in weights]
        wd_tuple = tuple(wds[k] for k in b.keys)
        fn = self._shard_program(b, rule_name, opt_params, wd_tuple,
                                 sentinel, scaling)
        lr_vec = np.asarray([lrs[k] for k in b.keys], np.float32)
        args = (tuple(parts), tuple(w_raws), b.shard_state, lr_vec)
        if scaling:
            sc = scale_raw
            sh = getattr(sc, "sharding", None)
            if sh is not None and sh.device_set != b.tset:
                sc = jax.device_put(
                    sc, NamedSharding(b.shard_mesh, P()))
            args = args + (sc,)
        res = fn(*args)
        if bi not in self._cost_done and _tm.perf.enabled():
            # perf plane: cost row under the plan-time memory row's label
            self._cost_done.add(bi)
            _tm.perf.attach_cost_analysis(
                f"kv_bucket{bi}[{np.dtype(b.dtype).name}x{len(b.keys)}"
                + (f"/shard{b.shard_n}" if b.shard_n > 1 else "")
                + ("/mp" if b.mp else "") + "]",
                fn, *args)
        new_w, new_s = res[0], res[1]
        flag = res[-1] if scaling else None
        if sentinel:
            _tm.health.sentinel_record(
                site=f"kv_bucket{bi}", step=self._push_count,
                names=[self._key_name(k) for k in b.keys],
                finite=res[2], packed_norm=True)
        b.shard_state = tuple(new_s)
        for i, w in enumerate(weights):
            w._chunk.write(new_w[i])
        if _tm.enabled():
            from .kvstore import _TM_PUSH, _TM_PUSH_BYTES

            _TM_PUSH.inc(len(b.keys), store=kv.type)
            _TM_PUSH_BYTES.inc(b.nbytes, store=kv.type)
            itemsize = np.dtype(b.wdtype).itemsize
            _executor._TM_COLLECTIVE.inc(b.padded * itemsize,
                                         op="kv_param_allgather")
            _executor._TM_COLLECTIVE.inc(
                b.padded * np.dtype(b.dtype).itemsize // b.shard_n,
                op="kv_grad_shard")
        return flag

    def _state_fingerprints(self, b):
        """{key: ((chunk, version), ...)} of the per-key state NDArrays
        the Updater currently holds for this bucket's keys — the change
        detector for eager interleaves / load_optimizer_states."""
        cur = {}
        for k in b.keys:
            st = self._updater.states.get(k)
            if st is None:
                continue
            slots = _state_slots(st)
            cur[k] = tuple((s._chunk, s._chunk.version) for s in slots)
        return cur

    def _ensure_shard_state(self, b):
        """(Re)build the bucket's sharded flat state vectors.

        Fresh training never materializes full per-key state: absent
        Updater entries ingest as zeros directly into the sharded
        layout (the 1/N-bytes-per-replica property).  Keys that DO have
        per-key state (an eager interlude, load_optimizer_states, a
        checkpoint restore) are folded in, and their (chunk, version)
        fingerprints recorded so any outside write triggers a
        re-ingest on the next sharded step.

        Under ``b.mp`` the LAST slot is the fp32 master: rule slots
        ingest fp32, and an absent master initializes from the stored
        (bf16) weight itself — upcast, never zeros."""
        cur = self._state_fingerprints(b)
        if b.shard_state is not None and cur == b.shard_src:
            return
        total_slots = b.nslots + (1 if b.mp else 0)
        flats = []
        for s in range(total_slots):
            is_master = b.mp and s == total_slots - 1
            dt = np.float32 if b.mp else np.dtype(b.wdtype)
            segs = []
            for i, k in enumerate(b.keys):
                st = self._updater.states.get(k)
                slots = _state_slots(st) if st is not None else ()
                if s < len(slots):
                    segs.append(jnp.ravel(slots[s]._read()).astype(dt))
                elif is_master:
                    w = self._kv._store[k]
                    segs.append(jnp.ravel(w._read()).astype(jnp.float32))
                else:
                    segs.append(jnp.zeros(b.sizes[i], dtype=dt))
            flat = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
            flat = jnp.pad(flat, (0, b.padded - int(sum(b.sizes))))
            flats.append(jax.device_put(flat, b.shard_sharding))
        b.shard_state = tuple(flats)
        b.shard_src = cur

    def sync_shard_state(self):
        """Materialize every sharded flat state vector back into the
        per-key NDArrays the eager ``Updater`` owns (the ONLY
        device→host path of the sharded engine — called at plan
        rebuilds, save/load_optimizer_states, and before any eager
        per-key update, never per step)."""
        buckets = [b for b in (self._buckets or ())
                   if b.shard_state is not None]
        if not buckets:
            return
        t0 = time.perf_counter() if _tm.enabled() else None
        for b in buckets:
            fulls = [np.asarray(f) for f in b.shard_state]
            for i, k in enumerate(b.keys):
                w = self._kv._store.get(k)
                if w is None:
                    continue
                slots = _state_slots(self._updater.ensure_state(k, w))
                for s, s_nd in enumerate(slots):
                    seg = fulls[s][b.offsets[i]:b.offsets[i] + b.sizes[i]]
                    s_nd._chunk.write(
                        jnp.asarray(seg.reshape(b.shapes[i])).astype(
                            s_nd.dtype))
            b.shard_src = self._state_fingerprints(b)
        if t0 is not None:
            _TM_SHARD_GATHER.observe(time.perf_counter() - t0,
                                     store=self._kv.type)

    # public alias the kvstore's eager paths call before touching the
    # per-key state store (a no-op flag check when nothing is sharded)
    ensure_host_state = sync_shard_state

    @property
    def shard_replicas(self) -> int:
        """Replica count of the sharded plan (1 = replicated)."""
        return max([b.shard_n for b in self._buckets or ()] or [1])

    def state_memory(self) -> dict:
        """Optimizer-state residency of the current plan: global bytes
        vs bytes per replica (the arXiv:2004.13336 saving, asserted by
        tests and emitted by bench.py's shard section).  AMP master
        weights are state slots, so they are counted here — the
        ``master_*`` fields break them out (a sharded mp bucket holds
        1/N master bytes per replica; docs/amp.md)."""
        per_replica = 0
        global_b = 0
        master_global = 0
        master_per_replica = 0
        sharded = 0
        for b in self._buckets or ():
            if b.shard_state is not None:
                bytes_ = sum(int(f.size) * np.dtype(f.dtype).itemsize
                             for f in b.shard_state)
                global_b += bytes_
                per_replica += bytes_ // b.shard_n
                if b.mp:
                    mb = int(b.shard_state[-1].size) * 4
                    master_global += mb
                    master_per_replica += mb // b.shard_n
                sharded += 1
            else:
                bytes_ = 0
                for k in b.keys:
                    slots = _state_slots(self._updater.states.get(k))
                    for s_nd in slots:
                        bytes_ += int(s_nd.size) * \
                            np.dtype(s_nd.dtype).itemsize
                    if b.mp and slots:
                        mb = int(slots[-1].size) * 4
                        master_global += mb
                        master_per_replica += mb
                global_b += bytes_
                per_replica += bytes_  # replicated: every replica holds all
        for sb in self._sparse_buckets:
            slots = _state_slots(self._updater.states.get(sb.key))
            bytes_ = 0
            for s_nd in slots:
                bytes_ += int(s_nd.size) * np.dtype(s_nd.dtype).itemsize
            if sb.mp and slots:
                mb = int(slots[-1].size) * 4
                master_global += mb
                master_per_replica += mb
            global_b += bytes_
            per_replica += bytes_
        return {"global_bytes": global_b, "per_replica_bytes": per_replica,
                "master_bytes": master_global,
                "master_bytes_per_replica": master_per_replica,
                "sharded_buckets": sharded,
                "replicas": self.shard_replicas}

    def _shard_program(self, b, rule_name, opt_params, wd_tuple,
                       sentinel=False, scaling=False):
        mesh = b.shard_mesh
        mesh_sig = (mesh.axis_names, mesh.devices.shape,
                    tuple(d.id for d in mesh.devices.flat))
        key = ("kvshard", rule_name, tuple(sorted(opt_params.items())),
               b.dtype.str, np.dtype(b.wdtype).str, tuple(b.shapes),
               wd_tuple, mesh_sig, sentinel)
        if b.mp or scaling:
            # AMP axes join the key only when active, so AMP-off runs
            # keep the exact pre-AMP cache keys (bit-identity contract)
            key = key + (("amp", b.mp, scaling),)
        fn = _executor.program_cache_get(key)
        if fn is None:
            fn = self._local_programs.get(key)
            if fn is None:
                fn = _make_sharded_bucket_program(
                    rule_name, opt_params, tuple(b.shapes),
                    tuple(b.sizes), wd_tuple, b.wdtype, mesh, sentinel,
                    mp=b.mp, scaling=scaling)
                _executor.program_cache_put(key, fn)
        self._local_programs[key] = fn
        return fn

    def _program(self, b, rule_name, opt_params, wd_tuple, sentinel=False,
                 scaling=False):
        key = ("kvfused", rule_name, tuple(sorted(opt_params.items())),
               b.dtype.str, tuple(b.shapes), wd_tuple, sentinel)
        if b.mp or scaling:
            key = key + (("amp", b.mp, np.dtype(b.wdtype).str
                          if b.wdtype is not None else None, scaling),)
        fn = _executor.program_cache_get(key)
        if fn is None:
            fn = self._local_programs.get(key)
            if fn is None:
                fn = _make_bucket_program(rule_name, opt_params,
                                          tuple(b.shapes), tuple(b.sizes),
                                          wd_tuple, sentinel,
                                          mp=b.mp, wdtype=b.wdtype,
                                          scaling=scaling)
                _executor.program_cache_put(key, fn)
        self._local_programs[key] = fn
        return fn

    # ----------------------------------------------------------------- pull
    def handle_pull(self, keys, outs) -> bool:
        """Bucket-sliced broadcast of stored values into the out arrays.

        Outs sharing the store's device set adopt the updated buffers by
        chunk rebind — zero device dispatches per key; cross-device outs
        get an explicit device_put preserving their placement."""
        kv = self._kv
        if any(k not in kv._store for k in keys):
            return False
        for o in outs:
            for oo in (o if isinstance(o, (list, tuple)) else [o]):
                if getattr(oo, "stype", "default") != "default":
                    return False  # sparse outs: the eager path decides
        t0 = time.perf_counter() if _tm.enabled() else None
        ncopies = 0
        nbytes = 0
        for k, o in zip(keys, outs):
            raw = kv._store[k]._read()
            targets = o if isinstance(o, (list, tuple)) else [o]
            for oo in targets:
                if oo._index is not None or oo._shape is not None:
                    oo._set(raw)  # view targets keep write-through
                    continue
                old = oo._chunk.data
                if old.sharding.device_set != raw.sharding.device_set:
                    oo._chunk.write(jax.device_put(raw, old.sharding))
                else:
                    oo._chunk.write(raw)
            ncopies += len(targets)
            nbytes += int(raw.size) * np.dtype(raw.dtype).itemsize \
                * len(targets)
        if t0 is not None:
            from .kvstore import _TM_PULL, _TM_PULL_BYTES, _TM_PULL_SEC

            _TM_PULL.inc(len(keys), store=kv.type)
            _TM_PULL_BYTES.inc(nbytes, store=kv.type)
            _TM_PULL_SEC.observe(time.perf_counter() - t0, store=kv.type)
        return True
