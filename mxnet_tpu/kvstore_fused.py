"""Bucketed, jit-fused KVStore update engine.

The eager update path (kvstore.py push/pull loops + optimizer.py
per-key ``update()``) pays one Python round-trip, one device copy, one
reduction, and one updater dispatch **per parameter** per step — ~300
tiny dispatches for a 100-param net.  This engine restructures the
Module step's kvstore half the way arXiv:2004.13336 restructures the
weight update and TVM (arXiv:1802.04799) argues for operator fusion:

- registered keys are grouped into size-capped **flat buckets**
  (``MXTPU_KV_BUCKET_MB``, default ~4MB; stable key order,
  dtype-segregated — a param bigger than the cap gets its own bucket),
- each bucket's per-device gradient copies are reduced with **one
  compiled reduction per bucket** (flatten+concat per source device,
  one transfer per device to the bucket's least-loaded merge device,
  one flat add) instead of one reduction per key,
- the optimizer update for every key in the bucket runs inside a
  **single jitted program** — the multi-tensor rules from
  optim_rules.py (shared with FusedTrainer) tree-mapped over the
  bucket's slices; optimizer state lives in the same NDArrays the eager
  ``Updater`` owns but stays **device-resident** (placed once, never
  re-materialized through ``as_in_context`` per step),
- pull becomes a bucket-sliced broadcast: out arrays adopt the updated
  buffers by chunk rebind when they share the store's devices (zero
  dispatches), with an explicit device_put only across device sets.

Per-step lr (including Adam's host-side bias correction) enters the
program as a traced scalar, so lr schedules never retrace; everything
else (bucket layout, optimizer hyperparams, per-key wd) is static and
forms the program's key in the executor's process-wide LRU
(``program_cache_get/put``) — rebinds, plan rebuilds, and new engine
instances reuse the compiled programs, visible as
``executor_graph_cache_total`` hits.

Eager per-key behavior stays available via ``MXTPU_FUSED_UPDATE=0`` and
remains the fallback for ``dist_*`` stores, custom Python updaters,
optimizers without a fused rule (``Optimizer.fused_rule()`` is None),
and pushes the engine cannot bucket (unregistered keys, ragged
per-device copy lists).  Interleaving eager and fused steps is safe:
both paths share the ``Updater``'s state store and the kvstore's value
NDArrays.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import executor as _executor
from . import telemetry as _tm
from .ndarray import NDArray
from .optim_rules import _RULES

# --- telemetry families (docs/telemetry.md) --------------------------------
_TM_FUSED_SEC = _tm.histogram(
    "kvstore_fused_update_seconds",
    "wall time of one batched fused push (bucket reductions + jitted "
    "multi-tensor optimizer updates; dispatch, not device completion)",
    labels=("store",))
_TM_BUCKET_COUNT = _tm.gauge(
    "kvstore_bucket_count",
    "flat buckets in the current fused-update plan", labels=("store",))
_TM_BUCKET_BYTES = _tm.histogram(
    "kvstore_bucket_bytes",
    "bytes per flat bucket at plan build (dtype-segregated, capped by "
    "MXTPU_KV_BUCKET_MB)", labels=("store",),
    buckets=(1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20,
             1 << 22, 1 << 23, 1 << 24, 1 << 26))

_DEFAULT_BUCKET_MB = 4.0


def fused_update_enabled() -> bool:
    """MXTPU_FUSED_UPDATE gate (default on)."""
    from .base import parse_bool

    return parse_bool(os.environ.get("MXTPU_FUSED_UPDATE", "1"))


def bucket_cap_bytes() -> int:
    """Resolved MXTPU_KV_BUCKET_MB cap in bytes (fractions allowed)."""
    raw = os.environ.get("MXTPU_KV_BUCKET_MB", "").strip()
    try:
        mb = float(raw) if raw else _DEFAULT_BUCKET_MB
    except ValueError:
        mb = _DEFAULT_BUCKET_MB
    return max(int(mb * (1 << 20)), 1)


def _lead_device(raw):
    """Deterministic representative device of a (possibly sharded) array."""
    return sorted(raw.sharding.device_set, key=lambda d: d.id)[0]


def _state_slots(state) -> Tuple[NDArray, ...]:
    """Updater state container -> the rule's tuple layout (None -> (),
    single NDArray -> 1 slot, tuple -> as-is)."""
    if state is None:
        return ()
    if isinstance(state, (tuple, list)):
        return tuple(state)
    return (state,)


def _make_bucket_program(rule_name, opt_params, shapes, sizes, wds,
                         sentinel=False):
    """One jitted program for a bucket: flatten+concat each device's
    grads, ONE flat reduction across devices, then the per-key slices
    run the shared update rule — XLA fuses the whole chain.  ``lrs``
    are traced scalars; shapes/sizes/wds/hyperparams are static.

    With ``sentinel`` (MXTPU_SENTINEL) the program ALSO returns a
    per-key isfinite mask and the bucket's gradient-norm scalar —
    computed inside the already-jitted chain, returned as device
    futures the health layer syncs only at reporting boundaries."""
    init_state, update = _RULES[rule_name](dict(opt_params))
    del init_state  # states come pre-created through the Updater

    def bucket_step(dev_parts, weights, states, lrs):
        flats = []
        for part in dev_parts:
            if isinstance(part, (tuple, list)):
                segs = [jnp.ravel(g) for g in part]
                flats.append(segs[0] if len(segs) == 1
                             else jnp.concatenate(segs))
            else:  # pre-concatenated on the source device
                flats.append(jnp.ravel(part))
        merged = flats[0]
        for f in flats[1:]:
            merged = merged + f
        new_w, new_s = [], []
        fins = []
        off = 0
        for i, shape in enumerate(shapes):
            g = merged[off:off + sizes[i]].reshape(shape)
            off += sizes[i]
            if sentinel:
                fins.append(jnp.isfinite(g).all())
            # lrs is ONE stacked traced vector (not n scalar leaves —
            # pytree flattening cost scales with leaf count on every
            # dispatch); lrs[i] is the key's traced scalar lr
            nw, ns = update(weights[i], g, states[i], lrs[i], wds[i])
            new_w.append(nw)
            new_s.append(tuple(ns))
        if sentinel:
            # per-key flags + the bucket's grad norm, packed into ONE
            # extra output leaf (norm rides as the last entry)
            fin_vec = jnp.stack(fins).astype(jnp.float32)
            gnorm = jnp.sqrt(
                jnp.sum(jnp.square(merged.astype(jnp.float32))))
            return (tuple(new_w), tuple(new_s),
                    jnp.concatenate([fin_vec, gnorm[None]]))
        return tuple(new_w), tuple(new_s)

    return jax.jit(_executor._count_traces(bucket_step, "kv_update"))


_concat_flat = None


def _concat(parts):
    """Jitted flatten+concat, run on the parts' (source) device."""
    global _concat_flat
    if _concat_flat is None:
        _concat_flat = jax.jit(_executor._count_traces(
            lambda ps: jnp.concatenate([jnp.ravel(p) for p in ps]),
            "kv_concat"))
    return _concat_flat(tuple(parts))


class _Bucket:
    __slots__ = ("dtype", "keys", "shapes", "sizes", "nbytes",
                 "target", "tset")

    def __init__(self, dtype):
        self.dtype = dtype
        self.keys: List = []
        self.shapes: List[Tuple[int, ...]] = []
        self.sizes: List[int] = []
        self.nbytes = 0
        self.target = None   # jax Sharding the bucket executes under
        self.tset = None     # its device_set (cheap placement guard)


class FusedUpdateEngine:
    """Drives the bucketed fused update for one KVStore instance.

    Created by ``KVStore.set_optimizer`` when the optimizer exposes a
    fused rule; ``handle_push``/``handle_pull`` return False when a call
    is not bucketable so the store falls back to the eager loops."""

    def __init__(self, kvstore, optimizer, updater):
        self._kv = kvstore
        self._opt = optimizer
        self._updater = updater
        self._buckets: Optional[List[_Bucket]] = None
        self._plan_keys: Optional[Tuple] = None
        self._key_index: Dict = {}
        self._ndev = 0
        self._load: Dict = {}       # merge-device -> assigned bucket bytes
        self._local_programs: Dict = {}  # fallback when the LRU is off
        self._push_count = 0        # the sentinel's step id for this store

    @property
    def num_buckets(self) -> int:
        return len(self._buckets or ())

    # ----------------------------------------------------------------- plan
    def _build_plan(self, keys, vlists, ndev):
        cap = bucket_cap_bytes()
        buckets: List[_Bucket] = []
        cur = None
        for i, _k in enumerate(keys):
            g0 = vlists[i][0]._read()
            dt = np.dtype(g0.dtype)
            size = int(g0.size)
            nbytes = size * dt.itemsize
            if (cur is None or cur.dtype != dt
                    or (cur.nbytes and cur.nbytes + nbytes > cap)):
                cur = _Bucket(dt)
                buckets.append(cur)
            cur.keys.append(keys[i])
            cur.shapes.append(tuple(g0.shape))
            cur.sizes.append(size)
            cur.nbytes += nbytes
        idx = {k: i for i, k in enumerate(keys)}
        for b in buckets:
            raws = [vlists[idx[b.keys[0]]][d]._read() for d in range(ndev)]
            if ndev == 1:
                # single (possibly mesh-global) grad per key: execute
                # where the gradients already live — zero grad transfers
                b.target = raws[0].sharding
            else:
                # per-device copies: least-loaded merge device among the
                # copies' devices, per bucket (parity: CommDevice::
                # InitMergeBuffer load balancing, comm.h:321-348, lifted
                # from per-key to per-bucket granularity)
                cands = sorted({_lead_device(r) for r in raws},
                               key=lambda d: (d.platform, d.id))
                dev = min(cands, key=lambda d: self._load.get(d, 0))
                self._load[dev] = self._load.get(dev, 0) + b.nbytes
                b.target = jax.sharding.SingleDeviceSharding(dev)
            b.tset = b.target.device_set
            if _tm.enabled():
                _TM_BUCKET_BYTES.observe(b.nbytes, store=self._kv.type)
        for i, b in enumerate(buckets):
            # memory attribution row per bucket program: ndev grad
            # copies + weights in, weights (+ state, roughly weight-
            # sized per slot) out — shape math, good enough to RANK
            # programs in the OOM report
            _tm.health.record_program(
                f"kv_bucket{i}[{np.dtype(b.dtype).name}x{len(b.keys)}]",
                argument=b.nbytes * (ndev + 2), output=b.nbytes * 2,
                temp=b.nbytes, source="shape_math")
        self._buckets = buckets
        self._plan_keys = tuple(keys)
        self._key_index = idx
        self._ndev = ndev
        if _tm.enabled():
            _TM_BUCKET_COUNT.set(len(buckets), store=self._kv.type)

    # ----------------------------------------------------------------- push
    def handle_push(self, keys, values) -> bool:
        """Run the fused bucketed update for one batched push; False if
        this call is not bucketable (caller falls back to eager)."""
        kv = self._kv
        vlists = [list(v) if isinstance(v, (list, tuple)) else [v]
                  for v in values]
        if not vlists:
            return False
        ndev = len(vlists[0])
        if ndev == 0:
            return False
        for k, vl in zip(keys, vlists):
            if k not in kv._store or len(vl) != ndev:
                return False
        t0 = time.perf_counter() if _tm.enabled() else None
        if self._plan_keys != tuple(keys) or self._ndev != ndev:
            self._build_plan(keys, vlists, ndev)
        opt = self._opt
        # host bookkeeping first (eager order: every key of the step sees
        # the same num_update), then the per-key traced lr / static wd
        for k in keys:
            opt._update_count(k)
        lrs = {k: float(opt.fused_lr(k)) for k in keys}
        wds = {k: float(opt._get_wd(k)) for k in keys}
        rule_name, opt_params = opt.fused_rule()
        self._push_count += 1
        try:
            for bi, b in enumerate(self._buckets):
                self._step_bucket(b, bi, vlists, rule_name, opt_params,
                                  lrs, wds)
        except Exception as e:  # noqa: BLE001 — OOM gets a report
            _tm.health.reraise_if_oom(e, site="kvstore_fused.push")
            raise
        if t0 is not None:
            _TM_FUSED_SEC.observe(time.perf_counter() - t0,
                                  store=kv.type)
        return True

    def _key_name(self, k):
        """Kvstore key -> the human name the sentinel reports (the
        optimizer's param_idx2name mapping when keys are indices)."""
        if isinstance(k, str):
            return k
        name = getattr(self._opt, "idx2name", {}).get(k)
        return name if name else str(k)

    def _place(self, nd_arr, target, tset):
        """Device-resident guard: returns the raw array, migrating the
        NDArray's chunk to the bucket's placement if (and only if) its
        device set differs — a metadata compare per step, a transfer
        only on the first fused step or after an eager interlude."""
        raw = nd_arr._read()
        if raw.sharding.device_set != tset:
            raw = jax.device_put(raw, target)
            nd_arr._chunk.write(raw)
        return raw

    def _step_bucket(self, b, bi, vlists, rule_name, opt_params, lrs, wds):
        kv, upd = self._kv, self._updater
        sentinel = _tm.health.sentinel_mode() is not None
        weights = [kv._store[k] for k in b.keys]
        slot_lists = [
            _state_slots(upd.ensure_state(k, w))
            for k, w in zip(b.keys, weights)
        ]
        w_raws = [self._place(w, b.target, b.tset) for w in weights]
        s_raws = [tuple(self._place(s, b.target, b.tset) for s in slots)
                  for slots in slot_lists]
        idx = self._key_index
        if self._ndev == 1:
            parts = []
            for k in b.keys:
                g = vlists[idx[k]][0]._read()
                if g.sharding.device_set != b.tset:
                    g = jax.device_put(g, b.target)
                parts.append(g)
            dev_inputs = (tuple(parts),)
        else:
            flats = []
            for d in range(self._ndev):
                segs = [vlists[idx[k]][d]._read() for k in b.keys]
                # flatten+concat ON the source device, then ONE transfer
                # per device per bucket to the merge device
                flat = jnp.ravel(segs[0]) if len(segs) == 1 \
                    else _concat(segs)
                if flat.sharding.device_set != b.tset:
                    flat = jax.device_put(flat, b.target)
                flats.append(flat)
            dev_inputs = tuple(flats)
        wd_tuple = tuple(wds[k] for k in b.keys)
        fn = self._program(b, rule_name, opt_params, wd_tuple, sentinel)
        lr_vec = np.asarray([lrs[k] for k in b.keys], np.float32)
        if sentinel:
            new_w, new_s, sent_vec = fn(
                dev_inputs, tuple(w_raws), tuple(s_raws), lr_vec)
            # park the device future — NO sync here; sentinel_check
            # reads it at the next reporting boundary
            _tm.health.sentinel_record(
                site=f"kv_bucket{bi}", step=self._push_count,
                names=[self._key_name(k) for k in b.keys],
                finite=sent_vec, packed_norm=True)
        else:
            new_w, new_s = fn(dev_inputs, tuple(w_raws), tuple(s_raws),
                              lr_vec)
        for i, w in enumerate(weights):
            # outputs carry the bucket's placement by construction:
            # rebind the chunks directly (NDArray._set would device_put
            # back to the pre-migration sharding)
            w._chunk.write(new_w[i])
            for s_nd, s_raw in zip(slot_lists[i], new_s[i]):
                s_nd._chunk.write(s_raw)
        if _tm.enabled():
            from .kvstore import _TM_PUSH, _TM_PUSH_BYTES

            _TM_PUSH.inc(len(b.keys), store=kv.type)
            _TM_PUSH_BYTES.inc(b.nbytes, store=kv.type)

    def _program(self, b, rule_name, opt_params, wd_tuple, sentinel=False):
        key = ("kvfused", rule_name, tuple(sorted(opt_params.items())),
               b.dtype.str, tuple(b.shapes), wd_tuple, sentinel)
        fn = _executor.program_cache_get(key)
        if fn is None:
            fn = self._local_programs.get(key)
            if fn is None:
                fn = _make_bucket_program(rule_name, opt_params,
                                          tuple(b.shapes), tuple(b.sizes),
                                          wd_tuple, sentinel)
                _executor.program_cache_put(key, fn)
        self._local_programs[key] = fn
        return fn

    # ----------------------------------------------------------------- pull
    def handle_pull(self, keys, outs) -> bool:
        """Bucket-sliced broadcast of stored values into the out arrays.

        Outs sharing the store's device set adopt the updated buffers by
        chunk rebind — zero device dispatches per key; cross-device outs
        get an explicit device_put preserving their placement."""
        kv = self._kv
        if any(k not in kv._store for k in keys):
            return False
        t0 = time.perf_counter() if _tm.enabled() else None
        ncopies = 0
        nbytes = 0
        for k, o in zip(keys, outs):
            raw = kv._store[k]._read()
            targets = o if isinstance(o, (list, tuple)) else [o]
            for oo in targets:
                if oo._index is not None or oo._shape is not None:
                    oo._set(raw)  # view targets keep write-through
                    continue
                old = oo._chunk.data
                if old.sharding.device_set != raw.sharding.device_set:
                    oo._chunk.write(jax.device_put(raw, old.sharding))
                else:
                    oo._chunk.write(raw)
            ncopies += len(targets)
            nbytes += int(raw.size) * np.dtype(raw.dtype).itemsize \
                * len(targets)
        if t0 is not None:
            from .kvstore import _TM_PULL, _TM_PULL_BYTES, _TM_PULL_SEC

            _TM_PULL.inc(len(keys), store=kv.type)
            _TM_PULL_BYTES.inc(nbytes, store=kv.type)
            _TM_PULL_SEC.observe(time.perf_counter() - t0, store=kv.type)
        return True
