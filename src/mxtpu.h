/*
 * libmxtpu — native runtime for the TPU framework.
 *
 * TPU-native counterpart of the reference's C++ runtime core:
 *  - dependency engine (parity: src/engine/threaded_engine.{h,cc},
 *    include/mxnet/engine.h:75-229): device compute is scheduled by
 *    PjRt/XLA, so this engine schedules the *host-side* async work the
 *    reference also ran through its engine — IO prefetch, checkpoint
 *    writes, kvstore staging — with the same const/mutable var-ordering
 *    contract (writers serialized, readers parallel, per-var FIFO).
 *  - RecordIO (parity: dmlc-core recordio framing + InputSplit sharding):
 *    native frame scanner/writer so the data pipeline's record handling
 *    is not bottlenecked on Python.
 *  - pooled storage arena (parity: src/storage/pooled_storage_manager.h):
 *    size-class recycling for host staging buffers.
 */
#ifndef MXTPU_H_
#define MXTPU_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ------------------------------------------------------------------ engine */
typedef void (*mxe_fn_t)(void *ctx);

/* Create an engine with n worker threads (0 = hardware_concurrency). */
void *mxe_create(int num_threads);
void mxe_destroy(void *engine);

/* New variable handle; freed with the engine. */
int64_t mxe_new_var(void *engine);

/* Push an async op: fn(ctx) runs once all deps resolve.  const_vars are
 * read deps (parallel), mutable_vars write deps (serialized, FIFO per
 * var).  Duplicate or overlapping var lists are rejected with -1
 * (parity: ThreadedEngine::CheckDuplicate); unknown/freed var ids with
 * -2.  priority: higher runs first among ready ops. */
int mxe_push(void *engine, mxe_fn_t fn, void *ctx,
             const int64_t *const_vars, int num_const,
             const int64_t *mutable_vars, int num_mutable,
             int priority);

/* Like mxe_push, plus a retirement hook: done_fn(done_ctx) is invoked on
 * the worker thread strictly AFTER fn has returned.  Callers managing
 * closure lifetimes (ctypes trampolines) use it as the release point —
 * once done_fn fires, fn's stack frame and trampoline have fully
 * unwound, so freeing fn is safe. */
int mxe_push_ex(void *engine, mxe_fn_t fn, void *ctx,
                mxe_fn_t done_fn, void *done_ctx,
                const int64_t *const_vars, int num_const,
                const int64_t *mutable_vars, int num_mutable,
                int priority);

/* Block until all ops touching var have completed. */
int mxe_wait_for_var(void *engine, int64_t var);
/* Block until every pushed op has completed. */
void mxe_wait_all(void *engine);
/* Number of ops pushed but not yet completed. */
int64_t mxe_pending(void *engine);

/* ---------------------------------------------------------------- recordio */
/* Reader over one shard of a RecordIO file (part_index/num_parts as in
 * dmlc::InputSplit): byte-range split, then aligned to record magic. */
void *mxr_open(const char *path, int part_index, int num_parts);
void mxr_close(void *reader);
/* Next record: returns pointer valid until the following call, or NULL at
 * end of shard; *len receives the payload length. */
const uint8_t *mxr_next(void *reader, uint64_t *len);
void mxr_reset(void *reader);
/* Batched read: fill buf (capacity buf_cap bytes) with up to max_records
 * concatenated payloads; lens[i] receives each payload's length.  Returns
 * the number of records read (0 at end of shard).  One FFI crossing per
 * batch instead of per record. */
int64_t mxr_next_batch(void *reader, uint8_t *buf, uint64_t buf_cap,
                       uint64_t *lens, int64_t max_records);
/* Scan the whole file, filling offsets[] (at most cap); returns count. */
int64_t mxr_index(const char *path, uint64_t *offsets, int64_t cap);

void *mxr_writer_open(const char *path);
int mxr_write(void *writer, const uint8_t *buf, uint64_t len);
void mxr_writer_close(void *writer);

/* ------------------------------------------------------------- jpeg decode */
/* Header-only parse: fills w/h/c (c always 3: decode converts to RGB). */
int mxj_dims(const uint8_t *src, uint64_t len, uint32_t *w, uint32_t *h,
             uint32_t *c);
/* Full RGB8 decode into dst (capacity cap bytes, needs w*h*3).  Both
 * return 0 on success, -1 on malformed input.  Thread-safe, GIL-free. */
int mxj_decode(const uint8_t *src, uint64_t len, uint8_t *dst,
               uint64_t cap);

/* ----------------------------------------------------------------- storage */
/* Pooled aligned host allocator.  Freed blocks are recycled by
 * round-up-to-pow2 size class. */
void *mxs_alloc(uint64_t size);
void mxs_free(void *ptr);
void mxs_direct_free(void *ptr);   /* bypass pool */
uint64_t mxs_pool_bytes(void);      /* bytes held in free lists */
void mxs_release_all(void);         /* drop pooled blocks */

/* ---- predict-only C ABI (libmxtpu_predict.so; parity:
 * include/mxnet/c_predict_api.h).  Embeds CPython; XLA does the math.
 * dev_type: 1 = cpu, 2 = accelerator.  All return 0/-1; error text via
 * MXPredGetLastError(). */
const char *MXPredGetLastError(void);
int MXPredCreate(const char *symbol_json, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, void **out);
int MXPredSetInput(void *handle, const char *key, const float *data,
                   uint32_t size);
int MXPredForward(void *handle);
/* Pipelined inference: ForwardAsync dispatches without joining and hands
 * back a ticket; GetOutputAsync joins that ticket.  Keeping 2+ tickets in
 * flight overlaps input upload, compute, and output fetch across calls —
 * the transport-hiding path for remote/tunneled devices. */
int MXPredForwardAsync(void *handle, int64_t *out_ticket);
int MXPredGetOutputAsync(void *handle, int64_t ticket, uint32_t index,
                         float *data, uint32_t size);
int MXPredGetOutputShape(void *handle, uint32_t index, uint32_t **shape_data,
                         uint32_t *shape_ndim);
int MXPredGetOutput(void *handle, uint32_t index, float *data, uint32_t size);
int MXPredFree(void *handle);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_H_ */
