/*
 * Native JPEG decode for the data pipeline (parity: the reference decodes
 * with OpenCV/libjpeg inside OpenMP workers, src/io/image_aug_default.cc
 * + iter_image_recordio.cc:259-368 — decode never touches the Python
 * interpreter, so a thread pool scales past the GIL).
 *
 * Exported (mxtpu.h):
 *   mxj_dims(src, len, &w, &h, &c)          — header-only parse
 *   mxj_decode(src, len, dst, cap)          — full RGB8 decode into dst
 *
 * Returns 0 on success, -1 on any libjpeg error (corrupt stream etc.);
 * errors longjmp out of libjpeg and never abort the process.
 */
#include "mxtpu.h"

#include <csetjmp>
#include <cstdio>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr *err = reinterpret_cast<ErrorMgr *>(cinfo->err);
  std::longjmp(err->jump, 1);
}

void emit_message(j_common_ptr, int) {}  // silence warnings

}  // namespace

extern "C" {

int mxj_dims(const uint8_t *src, uint64_t len, uint32_t *w, uint32_t *h,
             uint32_t *c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.emit_message = emit_message;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, src, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *w = cinfo.image_width;
  *h = cinfo.image_height;
  *c = 3;  // decode path always converts to RGB
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int mxj_decode(const uint8_t *src, uint64_t len, uint8_t *dst,
               uint64_t cap) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  jerr.pub.emit_message = emit_message;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, src, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const uint64_t stride =
      static_cast<uint64_t>(cinfo.output_width) * cinfo.output_components;
  if (static_cast<uint64_t>(cinfo.output_height) * stride > cap) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + static_cast<uint64_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // extern "C"
