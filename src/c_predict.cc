/*
 * C predict ABI — standalone inference entry points for non-Python
 * frontends (parity: include/mxnet/c_predict_api.h +
 * src/c_api/c_predict_api.cc; the reference uses this for its
 * amalgamation/mobile/JNI builds).
 *
 * TPU-native design: the compute path IS the XLA runtime driven through
 * mxnet_tpu.predict.Predictor, so this layer embeds CPython and
 * forwards each C call to that class.  The first MXPredCreate
 * initializes the interpreter (no-op when the host app already embeds
 * Python); everything after SetInput/Forward runs compiled XLA — the
 * interpreter only marshals buffers.
 *
 * Exported surface (mxtpu.h):
 *   MXPredCreate, MXPredSetInput, MXPredForward, MXPredGetOutputShape,
 *   MXPredGetOutput, MXPredReshape, MXPredFree, MXPredGetLastError.
 * All functions return 0 on success, -1 on failure (error text via
 * MXPredGetLastError — thread-local, like the reference's c_api_error).
 */
#include "mxtpu.h"

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string last_error;

struct PredHandle {
  PyObject *predictor = nullptr;             // mxnet_tpu.predict.Predictor
  std::vector<std::vector<int64_t>> out_shapes;
  std::vector<std::vector<float>> out_bufs;  // filled by GetOutput
};

std::once_flag init_flag;
bool interpreter_ours = false;

void EnsurePython() {
  std::call_once(init_flag, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      interpreter_ours = true;
      // release the GIL acquired by initialization so the gil guards
      // below work uniformly
      PyEval_SaveThread();
    }
  });
}

struct GilGuard {
  PyGILState_STATE st;
  GilGuard() { st = PyGILState_Ensure(); }
  ~GilGuard() { PyGILState_Release(st); }
};

int Fail(const char *where) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  last_error = where;
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      last_error += ": ";
      last_error += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return -1;
}

}  // namespace

extern "C" {

const char *MXPredGetLastError() { return last_error.c_str(); }

int MXPredCreate(const char *symbol_json, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 uint32_t num_input_nodes, const char **input_keys,
                 const uint32_t *input_shape_indptr,
                 const uint32_t *input_shape_data, void **out) {
  EnsurePython();
  GilGuard gil;
  PyObject *mod = PyImport_ImportModule("mxnet_tpu.predict");
  if (!mod) return Fail("import mxnet_tpu.predict");
  PyObject *cls = PyObject_GetAttrString(mod, "Predictor");
  Py_DECREF(mod);
  if (!cls) return Fail("Predictor lookup");

  PyObject *shapes = PyDict_New();
  for (uint32_t i = 0; i < num_input_nodes; ++i) {
    uint32_t lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *tup = PyTuple_New(hi - lo);
    for (uint32_t j = lo; j < hi; ++j) {
      PyTuple_SET_ITEM(tup, j - lo, PyLong_FromLong(input_shape_data[j]));
    }
    PyDict_SetItemString(shapes, input_keys[i], tup);
    Py_DECREF(tup);
  }
  PyObject *params = param_bytes
      ? PyBytes_FromStringAndSize(static_cast<const char *>(param_bytes),
                                  param_size)
      : Py_NewRef(Py_None);
  const char *dev = (dev_type == 2) ? "tpu" : (dev_type == 1 ? "cpu" : "cpu");
  PyObject *kwargs = Py_BuildValue(
      "{s:s, s:O, s:O, s:s, s:i}", "symbol_json_str", symbol_json,
      "param_bytes", params, "input_shapes", shapes, "dev_type", dev,
      "dev_id", dev_id);
  Py_DECREF(params);
  Py_DECREF(shapes);
  PyObject *empty = PyTuple_New(0);
  PyObject *pred = PyObject_Call(cls, empty, kwargs);
  Py_DECREF(empty);
  Py_DECREF(kwargs);
  Py_DECREF(cls);
  if (!pred) return Fail("Predictor()");
  auto *h = new PredHandle;
  h->predictor = pred;
  *out = h;
  return 0;
}

int MXPredSetInput(void *handle, const char *key, const float *data,
                   uint32_t size) {
  auto *h = static_cast<PredHandle *>(handle);
  GilGuard gil;
  // hand the buffer over as a python list-free memoryview -> numpy
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) return Fail("import numpy");
  PyObject *mv = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<float *>(data)),
      static_cast<Py_ssize_t>(size) * 4, PyBUF_READ);
  PyObject *arr = PyObject_CallMethod(np, "frombuffer", "Os", mv, "float32");
  Py_DECREF(mv);
  Py_DECREF(np);
  if (!arr) return Fail("frombuffer");
  // reshape to the bound input shape
  PyObject *exec_arr = PyObject_CallMethod(h->predictor, "_input_shape", "s",
                                           key);
  PyObject *reshaped;
  if (exec_arr) {
    reshaped = PyObject_CallMethod(arr, "reshape", "O", exec_arr);
    Py_DECREF(exec_arr);
  } else {
    PyErr_Clear();
    reshaped = Py_NewRef(arr);
  }
  Py_DECREF(arr);
  if (!reshaped) return Fail("reshape");
  // the frombuffer view points at the CALLER's memory with no ownership;
  // Predictor.set_input copies it into python-owned memory before the
  // device upload (jax's cpu backend may alias host buffers zero-copy —
  // observed as intermittent zero-weight forwards when a freed caller
  // buffer's pages were reused), so the view is safe to hand over
  PyObject *r = PyObject_CallMethod(h->predictor, "set_input", "sO", key,
                                    reshaped);
  Py_DECREF(reshaped);
  if (!r) return Fail("set_input");
  Py_DECREF(r);
  return 0;
}

int MXPredForward(void *handle) {
  auto *h = static_cast<PredHandle *>(handle);
  GilGuard gil;
  PyObject *r = PyObject_CallMethod(h->predictor, "forward", nullptr);
  if (!r) return Fail("forward");
  Py_DECREF(r);
  return 0;
}

int MXPredForwardAsync(void *handle, int64_t *out_ticket) {
  auto *h = static_cast<PredHandle *>(handle);
  GilGuard gil;
  PyObject *t = PyObject_CallMethod(h->predictor, "forward_async", nullptr);
  if (!t) return Fail("forward_async");
  *out_ticket = PyLong_AsLongLong(t);
  Py_DECREF(t);
  if (PyErr_Occurred()) return Fail("forward_async ticket");
  return 0;
}

int MXPredGetOutputAsync(void *handle, int64_t ticket, uint32_t index,
                         float *data, uint32_t size) {
  auto *h = static_cast<PredHandle *>(handle);
  GilGuard gil;
  PyObject *out = PyObject_CallMethod(h->predictor, "get_async", "LI",
                                      static_cast<long long>(ticket), index);
  if (!out) return Fail("get_async");
  PyObject *ravel = PyObject_CallMethod(out, "ravel", nullptr);
  Py_DECREF(out);
  if (!ravel) return Fail("ravel");
  PyObject *bytes = PyObject_CallMethod(ravel, "tobytes", nullptr);
  Py_DECREF(ravel);
  if (!bytes) return Fail("tobytes");
  Py_ssize_t nbytes = PyBytes_Size(bytes);
  if (nbytes > static_cast<Py_ssize_t>(size) * 4) {
    Py_DECREF(bytes);
    last_error = "output buffer too small";
    return -1;
  }
  std::memcpy(data, PyBytes_AsString(bytes), nbytes);
  Py_DECREF(bytes);
  return 0;
}

int MXPredGetOutputShape(void *handle, uint32_t index, uint32_t **shape_data,
                         uint32_t *shape_ndim) {
  auto *h = static_cast<PredHandle *>(handle);
  GilGuard gil;
  PyObject *shp = PyObject_CallMethod(h->predictor, "get_output_shape", "I",
                                      index);
  if (!shp) return Fail("get_output_shape");
  Py_ssize_t n = PyTuple_Size(shp);
  if (h->out_shapes.size() <= index) h->out_shapes.resize(index + 1);
  auto &dst = h->out_shapes[index];
  dst.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    dst[i] = PyLong_AsLong(PyTuple_GetItem(shp, i));
  }
  Py_DECREF(shp);
  static thread_local std::vector<uint32_t> tmp;
  tmp.assign(dst.begin(), dst.end());
  *shape_data = tmp.data();
  *shape_ndim = static_cast<uint32_t>(n);
  return 0;
}

int MXPredGetOutput(void *handle, uint32_t index, float *data, uint32_t size) {
  auto *h = static_cast<PredHandle *>(handle);
  GilGuard gil;
  PyObject *out = PyObject_CallMethod(h->predictor, "get_output", "I", index);
  if (!out) return Fail("get_output");
  PyObject *flat = PyObject_CallMethod(out, "astype", "s", "float32");
  Py_DECREF(out);
  if (!flat) return Fail("astype");
  PyObject *ravel = PyObject_CallMethod(flat, "ravel", nullptr);
  Py_DECREF(flat);
  if (!ravel) return Fail("ravel");
  PyObject *bytes = PyObject_CallMethod(ravel, "tobytes", nullptr);
  Py_DECREF(ravel);
  if (!bytes) return Fail("tobytes");
  Py_ssize_t nbytes = PyBytes_Size(bytes);
  if (nbytes > static_cast<Py_ssize_t>(size) * 4) {
    Py_DECREF(bytes);
    last_error = "output buffer too small";
    return -1;
  }
  std::memcpy(data, PyBytes_AsString(bytes), nbytes);
  Py_DECREF(bytes);
  return 0;
}

int MXPredFree(void *handle) {
  auto *h = static_cast<PredHandle *>(handle);
  {
    GilGuard gil;
    Py_XDECREF(h->predictor);
  }
  delete h;
  return 0;
}

}  // extern "C"
