/*
 * RecordIO native reader/writer (parity: dmlc-core recordio framing as
 * consumed by src/io/iter_image_recordio.cc, plus dmlc::InputSplit's
 * part_index/num_parts byte-range sharding used for distributed readers).
 *
 * Frame format (bit-compatible with python/mxnet/recordio.py and our
 * mxnet_tpu/recordio.py): [magic u32 = 0xced7230a][len u32][payload]
 * [pad to 4B].
 */
#include "mxtpu.h"

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Reader {
  FILE *fp = nullptr;
  uint64_t begin = 0;   // shard start (aligned to a record)
  uint64_t end = 0;     // shard end: records *starting* before end belong
  uint64_t pos = 0;
  std::vector<uint8_t> buf;
};

uint64_t FileSize(FILE *fp) {
  long cur = std::ftell(fp);
  std::fseek(fp, 0, SEEK_END);
  long size = std::ftell(fp);
  std::fseek(fp, cur, SEEK_SET);
  return static_cast<uint64_t>(size);
}

// Scan forward from `from` to the first record boundary at or after it.
// A boundary is a magic word followed by a plausible length.
uint64_t AlignToRecord(FILE *fp, uint64_t from, uint64_t fsize) {
  if (from == 0) return 0;
  std::fseek(fp, static_cast<long>(from), SEEK_SET);
  // stream bytes looking for magic; check length sanity
  uint64_t off = from;
  uint32_t window = 0;
  int have = 0;
  for (; off < fsize; ++off) {
    int c = std::fgetc(fp);
    if (c == EOF) break;
    window = (window >> 8) | (static_cast<uint32_t>(c) << 24);
    ++have;
    if (have >= 4 && window == kMagic) {
      uint64_t start = off - 3;
      // validate: length word must keep the record inside the file
      uint32_t len;
      if (std::fread(&len, 4, 1, fp) != 1) break;
      uint64_t payload_end = start + 8 + len;
      std::fseek(fp, static_cast<long>(off + 1), SEEK_SET);
      if (payload_end <= fsize) return start;
    }
  }
  return fsize;
}

}  // namespace

extern "C" {

void *mxr_open(const char *path, int part_index, int num_parts) {
  FILE *fp = std::fopen(path, "rb");
  if (!fp) return nullptr;
  auto *r = new Reader;
  r->fp = fp;
  uint64_t fsize = FileSize(fp);
  if (num_parts <= 1) {
    r->begin = 0;
    r->end = fsize;
  } else {
    uint64_t chunk = fsize / num_parts;
    uint64_t lo = chunk * part_index;
    uint64_t hi = (part_index == num_parts - 1) ? fsize
                                                : chunk * (part_index + 1);
    r->begin = AlignToRecord(fp, lo, fsize);
    r->end = (part_index == num_parts - 1) ? fsize
                                           : AlignToRecord(fp, hi, fsize);
  }
  r->pos = r->begin;
  std::fseek(fp, static_cast<long>(r->begin), SEEK_SET);
  return r;
}

void mxr_close(void *reader) {
  auto *r = static_cast<Reader *>(reader);
  if (r) {
    if (r->fp) std::fclose(r->fp);
    delete r;
  }
}

void mxr_reset(void *reader) {
  auto *r = static_cast<Reader *>(reader);
  r->pos = r->begin;
  std::fseek(r->fp, static_cast<long>(r->begin), SEEK_SET);
}

const uint8_t *mxr_next(void *reader, uint64_t *len) {
  auto *r = static_cast<Reader *>(reader);
  if (r->pos >= r->end) return nullptr;
  uint32_t header[2];
  if (std::fread(header, 4, 2, r->fp) != 2) return nullptr;
  if (header[0] != kMagic) return nullptr;
  uint32_t length = header[1];
  r->buf.resize(length);
  if (length > 0 && std::fread(r->buf.data(), 1, length, r->fp) != length) {
    return nullptr;
  }
  uint32_t pad = (4 - length % 4) % 4;
  if (pad) std::fseek(r->fp, pad, SEEK_CUR);
  r->pos += 8 + length + pad;
  *len = length;
  if (length == 0) {
    // vector::data() of an empty vector may be null, and callers use a
    // null return to mean end-of-shard; hand back a non-null sentinel
    // so zero-length records stay distinguishable from EOF
    static const uint8_t kEmpty = 0;
    return &kEmpty;
  }
  return r->buf.data();
}

int64_t mxr_next_batch(void *reader, uint8_t *buf, uint64_t buf_cap,
                       uint64_t *lens, int64_t max_records) {
  auto *r = static_cast<Reader *>(reader);
  int64_t count = 0;
  uint64_t used = 0;
  while (count < max_records && r->pos < r->end) {
    uint32_t header[2];
    long rollback = std::ftell(r->fp);
    if (std::fread(header, 4, 2, r->fp) != 2) break;
    if (header[0] != kMagic) break;
    uint32_t length = header[1];
    uint32_t pad = (4 - length % 4) % 4;
    if (used + length > buf_cap) {  // batch full: rewind this record
      std::fseek(r->fp, rollback, SEEK_SET);
      break;
    }
    if (length > 0 && std::fread(buf + used, 1, length, r->fp) != length) {
      break;
    }
    if (pad) std::fseek(r->fp, pad, SEEK_CUR);
    r->pos += 8 + length + pad;
    lens[count++] = length;
    used += length;
  }
  return count;
}

int64_t mxr_index(const char *path, uint64_t *offsets, int64_t cap) {
  FILE *fp = std::fopen(path, "rb");
  if (!fp) return -1;
  int64_t count = 0;
  uint64_t pos = 0;
  uint32_t header[2];
  while (std::fread(header, 4, 2, fp) == 2) {
    if (header[0] != kMagic) break;
    if (count < cap) offsets[count] = pos;
    ++count;
    uint32_t length = header[1];
    uint32_t pad = (4 - length % 4) % 4;
    if (std::fseek(fp, length + pad, SEEK_CUR) != 0) break;
    pos += 8 + length + pad;
  }
  std::fclose(fp);
  return count;
}

void *mxr_writer_open(const char *path) { return std::fopen(path, "wb"); }

int mxr_write(void *writer, const uint8_t *buf, uint64_t len) {
  FILE *fp = static_cast<FILE *>(writer);
  uint32_t header[2] = {kMagic, static_cast<uint32_t>(len)};
  if (std::fwrite(header, 4, 2, fp) != 2) return -1;
  if (len > 0 && std::fwrite(buf, 1, len, fp) != len) return -1;
  uint32_t pad = (4 - len % 4) % 4;
  static const uint8_t zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, fp) != pad) return -1;
  return 0;
}

void mxr_writer_close(void *writer) {
  if (writer) std::fclose(static_cast<FILE *>(writer));
}

}  // extern "C"
