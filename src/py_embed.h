/*
 * Shared CPython-embedding helpers for the C ABIs (c_predict.cc,
 * c_api.cc).  Each translation unit gets its own thread-local error
 * string + interpreter bootstrap (safe: Py_InitializeEx is guarded by
 * Py_IsInitialized, and both libs may be loaded into one process).
 */
#ifndef MXTPU_PY_EMBED_H_
#define MXTPU_PY_EMBED_H_

#include <Python.h>

#include <mutex>
#include <string>

namespace mxtpu_embed {

inline thread_local std::string last_error;

inline void EnsurePython() {
  static std::once_flag flag;
  std::call_once(flag, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL taken by initialization so GilGuard works
      // uniformly for embedder- and host-initialized interpreters
      PyEval_SaveThread();
    }
  });
}

struct GilGuard {
  PyGILState_STATE st;
  GilGuard() { st = PyGILState_Ensure(); }
  ~GilGuard() { PyGILState_Release(st); }
};

/* Capture the pending Python exception into last_error; returns -1. */
inline int Fail(const char *where) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  last_error = where;
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      last_error += ": ";
      last_error += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return -1;
}

/* import mxnet_tpu.<submodule> and return the module (new ref). */
inline PyObject *ImportImpl(const char *module) {
  PyObject *m = PyImport_ImportModule(module);
  return m;
}

}  // namespace mxtpu_embed

#endif  // MXTPU_PY_EMBED_H_
