/*
 * Pooled host storage arena (parity: src/storage/storage.cc +
 * pooled_storage_manager.h — GPUPooledStorageManager's size-class
 * recycling, applied to host staging buffers; device memory on TPU is
 * owned by PjRt/XLA buffer assignment).
 */
#include "mxtpu.h"

#include <cstdlib>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t kAlign = 64;

struct Pool {
  std::mutex mu;
  // size-class (rounded size) -> free blocks
  std::unordered_map<uint64_t, std::vector<void *>> free_list;
  // live ptr -> rounded size
  std::unordered_map<void *, uint64_t> sizes;
  uint64_t pooled_bytes = 0;
};

Pool &pool() {
  static Pool *p = new Pool;
  return *p;
}

uint64_t RoundSize(uint64_t size) {
  // round up to next power of two >= 256 (size-class recycling like
  // GPUPooledStorageManager's exact-size buckets but with bounded class
  // count)
  uint64_t r = 256;
  while (r < size) r <<= 1;
  return r;
}

}  // namespace

extern "C" {

void *mxs_alloc(uint64_t size) {
  uint64_t rounded = RoundSize(size);
  Pool &p = pool();
  {
    std::lock_guard<std::mutex> lk(p.mu);
    auto it = p.free_list.find(rounded);
    if (it != p.free_list.end() && !it->second.empty()) {
      void *ptr = it->second.back();
      it->second.pop_back();
      p.pooled_bytes -= rounded;
      p.sizes[ptr] = rounded;
      return ptr;
    }
  }
  void *ptr = nullptr;
  if (posix_memalign(&ptr, kAlign, rounded) != 0) return nullptr;
  std::lock_guard<std::mutex> lk(p.mu);
  p.sizes[ptr] = rounded;
  return ptr;
}

void mxs_free(void *ptr) {
  if (!ptr) return;
  Pool &p = pool();
  std::lock_guard<std::mutex> lk(p.mu);
  auto it = p.sizes.find(ptr);
  if (it == p.sizes.end()) return;
  p.free_list[it->second].push_back(ptr);
  p.pooled_bytes += it->second;
  p.sizes.erase(it);
}

void mxs_direct_free(void *ptr) {
  if (!ptr) return;
  Pool &p = pool();
  {
    std::lock_guard<std::mutex> lk(p.mu);
    p.sizes.erase(ptr);
  }
  std::free(ptr);
}

uint64_t mxs_pool_bytes(void) {
  Pool &p = pool();
  std::lock_guard<std::mutex> lk(p.mu);
  return p.pooled_bytes;
}

void mxs_release_all(void) {
  Pool &p = pool();
  std::lock_guard<std::mutex> lk(p.mu);
  for (auto &kv : p.free_list) {
    for (void *ptr : kv.second) std::free(ptr);
  }
  p.free_list.clear();
  p.pooled_bytes = 0;
}

}  // extern "C"
