/* _mxtpu_ext — CPython-C-API FFI backend over libmxtpu.
 *
 * Parity rationale (SURVEY.md §2.3, `_ctypes/` vs `cython/` row): the
 * reference ships two interchangeable FFI backends for its hot frontend
 * paths — ctypes (`python/mxnet/_ctypes/ndarray.py`) and a compiled one
 * (`python/mxnet/cython/ndarray.pyx`) — selected by MXNET_ENABLE_CYTHON.
 * This module is our compiled backend: the same libmxtpu runtime the
 * ctypes backend in mxnet_tpu/_native.py binds, reached through native
 * PyMethodDef calls instead of ctypes marshalling.  Selection is
 * per-object (backend=...) with the MXTPU_FFI env var as the global
 * default, mirroring the reference's env switch.
 *
 * What the compiled path buys (measured in tests/test_ffi_backends.py):
 *   - record batches are built as a list of PyBytes in one crossing with
 *     no intermediate staging buffer (the ctypes path fills a c_uint8
 *     arena, then slices it in Python);
 *   - engine ops carry a plain INCREF'd callable instead of a per-op
 *     ctypes CFUNCTYPE trampoline (whose allocation and lifetime
 *     tracking dominate small-op push cost);
 *   - storage arena views come back as writable memoryviews with no
 *     from_address() round trip.
 *
 * The runtime itself is shared: both backends drive the same engine
 * scheduler, the same recordio readers and the same storage pool, so
 * they are interchangeable mid-process.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstdlib>

#include "mxtpu.h"

namespace {

/* ---------------------------------------------------------------- */
/* capsule plumbing: a one-pointer box so close() can be idempotent  */
/* and the capsule destructor never double-frees                     */
/* ---------------------------------------------------------------- */
struct Box {
  void *h;
  void (*closer)(void *);
};

void box_capsule_destructor(PyObject *cap) {
  auto *box = static_cast<Box *>(
      PyCapsule_GetPointer(cap, PyCapsule_GetName(cap)));
  if (box != nullptr) {
    if (box->h != nullptr && box->closer != nullptr) box->closer(box->h);
    std::free(box);
  }
}

PyObject *box_new(void *handle, void (*closer)(void *), const char *name) {
  auto *box = static_cast<Box *>(std::malloc(sizeof(Box)));
  if (box == nullptr) return PyErr_NoMemory();
  box->h = handle;
  box->closer = closer;
  PyObject *cap = PyCapsule_New(box, name, box_capsule_destructor);
  if (cap == nullptr) {
    if (closer != nullptr) closer(handle);
    std::free(box);
  }
  return cap;
}

Box *box_get(PyObject *cap, const char *name) {
  auto *box = static_cast<Box *>(PyCapsule_GetPointer(cap, name));
  if (box == nullptr) return nullptr;
  if (box->h == nullptr) {
    PyErr_Format(PyExc_ValueError, "%s handle already closed", name);
    return nullptr;
  }
  return box;
}

constexpr const char *kReaderCap = "mxtpu.reader";
constexpr const char *kWriterCap = "mxtpu.writer";
constexpr const char *kEngineCap = "mxtpu.engine";

/* ---------------------------------------------------------------- */
/* RecordIO                                                          */
/* ---------------------------------------------------------------- */
PyObject *py_rec_open(PyObject *, PyObject *args) {
  const char *path;
  int part = 0, nparts = 1;
  if (!PyArg_ParseTuple(args, "s|ii", &path, &part, &nparts)) return nullptr;
  void *h = nullptr;
  Py_BEGIN_ALLOW_THREADS
  h = mxr_open(path, part, nparts);
  Py_END_ALLOW_THREADS
  if (h == nullptr) {
    PyErr_Format(PyExc_IOError, "cannot open %s", path);
    return nullptr;
  }
  return box_new(h, mxr_close, kReaderCap);
}

PyObject *py_rec_next(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  Box *box = box_get(cap, kReaderCap);
  if (box == nullptr) return nullptr;
  uint64_t len = 0;
  const uint8_t *ptr = nullptr;
  Py_BEGIN_ALLOW_THREADS
  ptr = mxr_next(box->h, &len);
  Py_END_ALLOW_THREADS
  if (ptr == nullptr) Py_RETURN_NONE;
  return PyBytes_FromStringAndSize(reinterpret_cast<const char *>(ptr),
                                   static_cast<Py_ssize_t>(len));
}

/* Up to max_records payloads in ONE crossing: the C loop reads records
 * and materializes each as PyBytes straight from the reader's buffer —
 * no staging arena, no Python-side slicing. */
PyObject *py_rec_next_batch(PyObject *, PyObject *args) {
  PyObject *cap;
  Py_ssize_t max_records = 1024;
  if (!PyArg_ParseTuple(args, "O|n", &cap, &max_records)) return nullptr;
  Box *box = box_get(cap, kReaderCap);
  if (box == nullptr) return nullptr;
  PyObject *out = PyList_New(0);
  if (out == nullptr) return nullptr;
  for (Py_ssize_t i = 0; i < max_records; ++i) {
    uint64_t len = 0;
    // reads are buffered stdio: cycling the GIL per record would cost
    // more than the read itself, so the loop holds it
    const uint8_t *ptr = mxr_next(box->h, &len);
    if (ptr == nullptr) break;
    PyObject *rec = PyBytes_FromStringAndSize(
        reinterpret_cast<const char *>(ptr), static_cast<Py_ssize_t>(len));
    if (rec == nullptr || PyList_Append(out, rec) != 0) {
      Py_XDECREF(rec);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(rec);
  }
  return out;
}

PyObject *py_rec_reset(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  Box *box = box_get(cap, kReaderCap);
  if (box == nullptr) return nullptr;
  mxr_reset(box->h);
  Py_RETURN_NONE;
}

PyObject *py_rec_close(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  auto *box = static_cast<Box *>(PyCapsule_GetPointer(cap, kReaderCap));
  if (box == nullptr) return nullptr;
  if (box->h != nullptr) {
    mxr_close(box->h);
    box->h = nullptr;
  }
  Py_RETURN_NONE;
}

PyObject *py_rec_index(PyObject *, PyObject *args) {
  const char *path;
  if (!PyArg_ParseTuple(args, "s", &path)) return nullptr;
  int64_t total = mxr_index(path, nullptr, 0);
  if (total < 0) {
    PyErr_Format(PyExc_IOError, "cannot open %s", path);
    return nullptr;
  }
  auto *buf = static_cast<uint64_t *>(
      std::malloc(sizeof(uint64_t) * static_cast<size_t>(total > 0 ? total : 1)));
  if (buf == nullptr) return PyErr_NoMemory();
  int64_t n = 0;
  Py_BEGIN_ALLOW_THREADS
  n = mxr_index(path, buf, total);
  Py_END_ALLOW_THREADS
  if (n < 0) {
    std::free(buf);
    PyErr_Format(PyExc_IOError, "cannot open %s", path);
    return nullptr;
  }
  if (n > total) n = total;
  PyObject *out = PyList_New(static_cast<Py_ssize_t>(n));
  if (out == nullptr) {
    std::free(buf);
    return nullptr;
  }
  for (int64_t i = 0; i < n; ++i) {
    PyObject *v = PyLong_FromUnsignedLongLong(buf[i]);
    if (v == nullptr) {
      std::free(buf);
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), v);
  }
  std::free(buf);
  return out;
}

void writer_closer(void *h) { mxr_writer_close(h); }

PyObject *py_rec_writer_open(PyObject *, PyObject *args) {
  const char *path;
  if (!PyArg_ParseTuple(args, "s", &path)) return nullptr;
  void *h = mxr_writer_open(path);
  if (h == nullptr) {
    PyErr_Format(PyExc_IOError, "cannot open %s for writing", path);
    return nullptr;
  }
  return box_new(h, writer_closer, kWriterCap);
}

PyObject *py_rec_write(PyObject *, PyObject *args) {
  PyObject *cap;
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "Oy*", &cap, &view)) return nullptr;
  Box *box = box_get(cap, kWriterCap);
  if (box == nullptr) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  int rc = 0;
  Py_BEGIN_ALLOW_THREADS
  rc = mxr_write(box->h, static_cast<const uint8_t *>(view.buf),
                 static_cast<uint64_t>(view.len));
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&view);
  if (rc != 0) {
    PyErr_SetString(PyExc_IOError, "record write failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject *py_rec_writer_close(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  auto *box = static_cast<Box *>(PyCapsule_GetPointer(cap, kWriterCap));
  if (box == nullptr) return nullptr;
  if (box->h != nullptr) {
    mxr_writer_close(box->h);
    box->h = nullptr;
  }
  Py_RETURN_NONE;
}

/* ---------------------------------------------------------------- */
/* Storage arena                                                     */
/* ---------------------------------------------------------------- */
PyObject *py_storage_alloc(PyObject *, PyObject *args) {
  unsigned long long nbytes;
  if (!PyArg_ParseTuple(args, "K", &nbytes)) return nullptr;
  if (nbytes == 0) nbytes = 1;
  void *ptr = mxs_alloc(nbytes);
  if (ptr == nullptr) {
    PyErr_Format(PyExc_MemoryError, "arena alloc of %llu bytes failed",
                 nbytes);
    return nullptr;
  }
  PyObject *view = PyMemoryView_FromMemory(
      static_cast<char *>(ptr), static_cast<Py_ssize_t>(nbytes), PyBUF_WRITE);
  if (view == nullptr) {
    mxs_free(ptr);
    return nullptr;
  }
  PyObject *addr = PyLong_FromVoidPtr(ptr);
  if (addr == nullptr) {
    Py_DECREF(view);
    mxs_free(ptr);
    return nullptr;
  }
  PyObject *tup = PyTuple_Pack(2, addr, view);
  Py_DECREF(addr);
  Py_DECREF(view);
  return tup;
}

PyObject *py_storage_free(PyObject *, PyObject *args) {
  unsigned long long addr;
  if (!PyArg_ParseTuple(args, "K", &addr)) return nullptr;
  mxs_free(reinterpret_cast<void *>(static_cast<uintptr_t>(addr)));
  Py_RETURN_NONE;
}

PyObject *py_storage_pool_bytes(PyObject *, PyObject *) {
  return PyLong_FromUnsignedLongLong(mxs_pool_bytes());
}

PyObject *py_storage_release_all(PyObject *, PyObject *) {
  mxs_release_all();
  Py_RETURN_NONE;
}

/* ---------------------------------------------------------------- */
/* Engine                                                            */
/* ---------------------------------------------------------------- */
struct OpCtx {
  PyObject *fn;        /* INCREF'd callable                          */
  PyObject *err_sink;  /* INCREF'd list; exceptions are appended     */
};

/* Runs on a C worker thread.  The GIL is taken only for the duration
 * of the Python call; the engine's scheduling itself never touches the
 * interpreter — that is the point of the compiled backend: no
 * per-op CFUNCTYPE object, no Python-side lifetime registry. */
extern "C" void op_trampoline(void *raw) {
  auto *op = static_cast<OpCtx *>(raw);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *res = PyObject_CallNoArgs(op->fn);
  if (res == nullptr) {
#if PY_VERSION_HEX >= 0x030C0000
    PyObject *exc = PyErr_GetRaisedException();
    if (exc != nullptr) {
      PyList_Append(op->err_sink, exc);
      Py_DECREF(exc);
    }
#else
    PyObject *type, *value, *tb;
    PyErr_Fetch(&type, &value, &tb);
    PyErr_NormalizeException(&type, &value, &tb);
    if (value != nullptr) PyList_Append(op->err_sink, value);
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
#endif
  } else {
    Py_DECREF(res);
  }
  Py_DECREF(op->fn);
  Py_DECREF(op->err_sink);
  PyGILState_Release(gil);
  std::free(op);
}

void engine_closer(void *h) { mxe_destroy(h); }

PyObject *py_eng_create(PyObject *, PyObject *args) {
  int num_threads = 0;
  if (!PyArg_ParseTuple(args, "|i", &num_threads)) return nullptr;
  void *h = mxe_create(num_threads);
  if (h == nullptr) {
    PyErr_SetString(PyExc_RuntimeError, "engine create failed");
    return nullptr;
  }
  return box_new(h, engine_closer, kEngineCap);
}

PyObject *py_eng_destroy(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  auto *box = static_cast<Box *>(PyCapsule_GetPointer(cap, kEngineCap));
  if (box == nullptr) return nullptr;
  if (box->h != nullptr) {
    void *h = box->h;
    box->h = nullptr;
    Py_BEGIN_ALLOW_THREADS
    mxe_destroy(h);
    Py_END_ALLOW_THREADS
  }
  Py_RETURN_NONE;
}

PyObject *py_eng_new_var(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  Box *box = box_get(cap, kEngineCap);
  if (box == nullptr) return nullptr;
  return PyLong_FromLongLong(mxe_new_var(box->h));
}

int64_t *vars_from_seq(PyObject *seq, Py_ssize_t *n_out) {
  PyObject *fast = PySequence_Fast(seq, "var list must be a sequence");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  auto *arr = static_cast<int64_t *>(
      std::malloc(sizeof(int64_t) * static_cast<size_t>(n > 0 ? n : 1)));
  if (arr == nullptr) {
    Py_DECREF(fast);
    PyErr_NoMemory();
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    arr[i] = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(fast, i));
    if (arr[i] == -1 && PyErr_Occurred()) {
      std::free(arr);
      Py_DECREF(fast);
      return nullptr;
    }
  }
  Py_DECREF(fast);
  *n_out = n;
  return arr;
}

PyObject *py_eng_push(PyObject *, PyObject *args) {
  PyObject *cap, *fn, *const_vars, *mutable_vars, *err_sink;
  int priority = 0;
  if (!PyArg_ParseTuple(args, "OOOOO|i", &cap, &fn, &const_vars,
                        &mutable_vars, &err_sink, &priority)) {
    return nullptr;
  }
  Box *box = box_get(cap, kEngineCap);
  if (box == nullptr) return nullptr;
  if (!PyCallable_Check(fn)) {
    PyErr_SetString(PyExc_TypeError, "fn must be callable");
    return nullptr;
  }
  if (!PyList_Check(err_sink)) {
    PyErr_SetString(PyExc_TypeError, "err_sink must be a list");
    return nullptr;
  }
  Py_ssize_t nc = 0, nm = 0;
  int64_t *carr = vars_from_seq(const_vars, &nc);
  if (carr == nullptr) return nullptr;
  int64_t *marr = vars_from_seq(mutable_vars, &nm);
  if (marr == nullptr) {
    std::free(carr);
    return nullptr;
  }
  auto *op = static_cast<OpCtx *>(std::malloc(sizeof(OpCtx)));
  if (op == nullptr) {
    std::free(carr);
    std::free(marr);
    return PyErr_NoMemory();
  }
  Py_INCREF(fn);
  Py_INCREF(err_sink);
  op->fn = fn;
  op->err_sink = err_sink;
  int rc = mxe_push(box->h, op_trampoline, op, carr, static_cast<int>(nc),
                    marr, static_cast<int>(nm), priority);
  std::free(carr);
  std::free(marr);
  if (rc != 0) {
    Py_DECREF(op->fn);
    Py_DECREF(op->err_sink);
    std::free(op);
    if (rc == -2) {
      PyErr_SetString(PyExc_ValueError,
                      "unknown engine var id in const/mutable var lists "
                      "(freed, or created on a different engine?)");
    } else {
      PyErr_SetString(PyExc_ValueError,
                      "duplicate or overlapping const/mutable var lists "
                      "(parity: ThreadedEngine::CheckDuplicate)");
    }
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject *py_eng_wait_for_var(PyObject *, PyObject *args) {
  PyObject *cap;
  long long var;
  if (!PyArg_ParseTuple(args, "OL", &cap, &var)) return nullptr;
  Box *box = box_get(cap, kEngineCap);
  if (box == nullptr) return nullptr;
  Py_BEGIN_ALLOW_THREADS
  mxe_wait_for_var(box->h, var);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

PyObject *py_eng_wait_all(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  Box *box = box_get(cap, kEngineCap);
  if (box == nullptr) return nullptr;
  Py_BEGIN_ALLOW_THREADS
  mxe_wait_all(box->h);
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

PyObject *py_eng_pending(PyObject *, PyObject *args) {
  PyObject *cap;
  if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
  Box *box = box_get(cap, kEngineCap);
  if (box == nullptr) return nullptr;
  return PyLong_FromLongLong(mxe_pending(box->h));
}

/* ---------------------------------------------------------------- */
PyMethodDef kMethods[] = {
    {"rec_open", py_rec_open, METH_VARARGS, "open a sharded record reader"},
    {"rec_next", py_rec_next, METH_VARARGS, "next record payload or None"},
    {"rec_next_batch", py_rec_next_batch, METH_VARARGS,
     "list of up to max_records payloads in one crossing"},
    {"rec_reset", py_rec_reset, METH_VARARGS, "rewind the reader shard"},
    {"rec_close", py_rec_close, METH_VARARGS, "close the reader"},
    {"rec_index", py_rec_index, METH_VARARGS, "record offsets of a file"},
    {"rec_writer_open", py_rec_writer_open, METH_VARARGS, "open a writer"},
    {"rec_write", py_rec_write, METH_VARARGS, "append one record"},
    {"rec_writer_close", py_rec_writer_close, METH_VARARGS,
     "close the writer"},
    {"storage_alloc", py_storage_alloc, METH_VARARGS,
     "(addr, writable memoryview) from the size-class arena"},
    {"storage_free", py_storage_free, METH_VARARGS,
     "recycle an arena block by address"},
    {"storage_pool_bytes", py_storage_pool_bytes, METH_NOARGS,
     "bytes held in arena free lists"},
    {"storage_release_all", py_storage_release_all, METH_NOARGS,
     "drop pooled arena blocks"},
    {"eng_create", py_eng_create, METH_VARARGS, "create an engine"},
    {"eng_destroy", py_eng_destroy, METH_VARARGS, "destroy an engine"},
    {"eng_new_var", py_eng_new_var, METH_VARARGS, "new dependency var"},
    {"eng_push", py_eng_push, METH_VARARGS,
     "push fn with (const_vars, mutable_vars, err_sink, priority)"},
    {"eng_wait_for_var", py_eng_wait_for_var, METH_VARARGS,
     "block until all ops touching var completed"},
    {"eng_wait_all", py_eng_wait_all, METH_VARARGS, "drain the engine"},
    {"eng_pending", py_eng_pending, METH_VARARGS, "ops not yet completed"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_mxtpu_ext",
    "compiled FFI backend over libmxtpu (counterpart of the ctypes "
    "backend in mxnet_tpu._native)",
    -1, kMethods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__mxtpu_ext(void) { return PyModule_Create(&kModule); }
