/*
 * Threaded dependency engine (parity: src/engine/threaded_engine.{h,cc} +
 * threaded_engine_perdevice.cc in the reference).
 *
 * Semantics reproduced exactly:
 *  - per-var FIFO queues; readers run in parallel, writers serialize
 *    (ThreadedVar::AppendRead/WriteDependency, threaded_engine.cc:82-103)
 *  - an op runs when all its var dependencies grant access; completion
 *    wakes successors (CompleteRead/WriteDependency)
 *  - overlapping const/mutable lists rejected (CheckDuplicate,
 *    threaded_engine.cc:207-239)
 *  - ready ops drain through a priority queue onto a worker pool
 *    (the reference's per-device pools collapse to one host pool here —
 *    device scheduling belongs to PjRt/XLA on TPU).
 */
#include "mxtpu.h"

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Opr;

// One entry in a var's pending queue.
struct VarBlock {
  Opr *opr;
  bool is_write;
};

struct Var {
  std::deque<VarBlock> queue;   // pending ops, FIFO
  int running_reads = 0;        // granted, not yet completed reads
  bool running_write = false;   // granted, not yet completed write
};

struct Opr {
  mxe_fn_t fn;
  void *ctx;
  mxe_fn_t done_fn = nullptr;       // fired after fn returns (see mxtpu.h)
  void *done_ctx = nullptr;
  std::vector<int64_t> const_vars;
  std::vector<int64_t> mutable_vars;
  int priority;
  uint64_t seq;                     // FIFO tiebreak within a priority
  std::atomic<int> wait{0};         // deps not yet granted
};

struct OprLess {
  bool operator()(const Opr *a, const Opr *b) const {
    if (a->priority != b->priority) return a->priority < b->priority;
    return a->seq > b->seq;  // earlier push first
  }
};

class Engine {
 public:
  explicit Engine(int num_threads) {
    if (num_threads <= 0) {
      num_threads = static_cast<int>(std::thread::hardware_concurrency());
      if (num_threads <= 0) num_threads = 4;
    }
    for (int i = 0; i < num_threads; ++i) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~Engine() {
    WaitAll();
    {
      std::unique_lock<std::mutex> lk(ready_mu_);
      shutdown_ = true;
    }
    ready_cv_.notify_all();
    for (auto &t : workers_) t.join();
  }

  int64_t NewVar() {
    std::lock_guard<std::mutex> lk(vars_mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, Var{});
    return id;
  }

  int Push(mxe_fn_t fn, void *ctx, const int64_t *cvars, int nc,
           const int64_t *mvars, int nm, int priority,
           mxe_fn_t done_fn = nullptr, void *done_ctx = nullptr) {
    // CheckDuplicate parity: no dup within or across lists
    std::vector<int64_t> c(cvars, cvars + nc), m(mvars, mvars + nm);
    std::sort(c.begin(), c.end());
    std::sort(m.begin(), m.end());
    if (std::adjacent_find(c.begin(), c.end()) != c.end()) return -1;
    if (std::adjacent_find(m.begin(), m.end()) != m.end()) return -1;
    std::vector<int64_t> inter;
    std::set_intersection(c.begin(), c.end(), m.begin(), m.end(),
                          std::back_inserter(inter));
    if (!inter.empty()) return -1;
    {
      // unknown var ids surface as -2 (vs -1 for duplicate/overlap), not
      // as a std::out_of_range unwinding through the C ABI (UB / abort)
      std::lock_guard<std::mutex> lk(vars_mu_);
      for (int64_t v : c)
        if (vars_.find(v) == vars_.end()) return -2;
      for (int64_t v : m)
        if (vars_.find(v) == vars_.end()) return -2;
    }

    auto *opr = new Opr;
    opr->fn = fn;
    opr->ctx = ctx;
    opr->done_fn = done_fn;
    opr->done_ctx = done_ctx;
    opr->const_vars.assign(cvars, cvars + nc);
    opr->mutable_vars.assign(mvars, mvars + nm);
    opr->priority = priority;
    pending_.fetch_add(1, std::memory_order_relaxed);

    int blocked = 0;
    {
      std::lock_guard<std::mutex> lk(vars_mu_);
      opr->seq = next_seq_++;
      // reserve wait so concurrent grants can't fire before all deps are
      // appended
      opr->wait.store(nc + nm + 1, std::memory_order_relaxed);
      for (int64_t v : opr->const_vars) {
        Var &var = vars_.at(v);
        if (var.queue.empty() && !var.running_write) {
          ++var.running_reads;            // grant immediately
          opr->wait.fetch_sub(1, std::memory_order_acq_rel);
        } else {
          var.queue.push_back({opr, false});
          ++blocked;
        }
      }
      for (int64_t v : opr->mutable_vars) {
        Var &var = vars_.at(v);
        if (var.queue.empty() && !var.running_write &&
            var.running_reads == 0) {
          var.running_write = true;       // grant immediately
          opr->wait.fetch_sub(1, std::memory_order_acq_rel);
        } else {
          var.queue.push_back({opr, true});
          ++blocked;
        }
      }
    }
    if (opr->wait.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      Enqueue(opr);
    }
    return 0;
  }

  int WaitForVar(int64_t var) {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
    struct W {
      std::mutex *mu;
      std::condition_variable *cv;
      bool *done;
    } w{&mu, &cv, &done};
    int rc = Push(
        [](void *p) {
          auto *w = static_cast<W *>(p);
          std::lock_guard<std::mutex> lk(*w->mu);
          *w->done = true;
          w->cv->notify_all();
        },
        &w, &var, 1, nullptr, 0, /*priority=*/1 << 30);
    if (rc != 0) return rc;
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
    return 0;
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(all_mu_);
    all_cv_.wait(lk, [this] {
      return pending_.load(std::memory_order_acquire) == 0;
    });
  }

  int64_t Pending() const {
    return pending_.load(std::memory_order_acquire);
  }

 private:
  void Enqueue(Opr *opr) {
    {
      std::lock_guard<std::mutex> lk(ready_mu_);
      ready_.push(opr);
    }
    ready_cv_.notify_one();
  }

  void WorkerLoop() {
    for (;;) {
      Opr *opr;
      {
        std::unique_lock<std::mutex> lk(ready_mu_);
        ready_cv_.wait(lk, [this] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        opr = ready_.top();
        ready_.pop();
      }
      opr->fn(opr->ctx);
      // fn's closure has fully unwound here: fire the retirement hook
      if (opr->done_fn) opr->done_fn(opr->done_ctx);
      OnComplete(opr);
      delete opr;
      if (pending_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        std::lock_guard<std::mutex> lk(all_mu_);
        all_cv_.notify_all();
      }
    }
  }

  // Release this op's grants and wake successors (parity:
  // ThreadedVar::CompleteReadDependency / CompleteWriteDependency).
  void OnComplete(Opr *opr) {
    std::vector<Opr *> to_run;
    {
      std::lock_guard<std::mutex> lk(vars_mu_);
      for (int64_t v : opr->const_vars) {
        Var &var = vars_.at(v);
        --var.running_reads;
        DrainLocked(&var, &to_run);
      }
      for (int64_t v : opr->mutable_vars) {
        Var &var = vars_.at(v);
        var.running_write = false;
        DrainLocked(&var, &to_run);
      }
    }
    for (Opr *o : to_run) Enqueue(o);
  }

  // Grant queued accesses now admissible; collect ops whose last dep just
  // resolved.  Must hold vars_mu_.
  void DrainLocked(Var *var, std::vector<Opr *> *to_run) {
    while (!var->queue.empty()) {
      VarBlock blk = var->queue.front();
      if (blk.is_write) {
        if (var->running_reads > 0 || var->running_write) break;
        var->running_write = true;
      } else {
        if (var->running_write) break;
        ++var->running_reads;
      }
      var->queue.pop_front();
      if (blk.opr->wait.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        to_run->push_back(blk.opr);
      }
      if (blk.is_write) break;  // writer holds the var exclusively
    }
  }

  std::mutex vars_mu_;
  std::unordered_map<int64_t, Var> vars_;
  int64_t next_var_ = 1;
  uint64_t next_seq_ = 0;

  std::mutex ready_mu_;
  std::condition_variable ready_cv_;
  std::priority_queue<Opr *, std::vector<Opr *>, OprLess> ready_;
  bool shutdown_ = false;

  std::mutex all_mu_;
  std::condition_variable all_cv_;
  std::atomic<int64_t> pending_{0};

  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void *mxe_create(int num_threads) { return new Engine(num_threads); }

void mxe_destroy(void *engine) { delete static_cast<Engine *>(engine); }

int64_t mxe_new_var(void *engine) {
  return static_cast<Engine *>(engine)->NewVar();
}

int mxe_push(void *engine, mxe_fn_t fn, void *ctx, const int64_t *const_vars,
             int num_const, const int64_t *mutable_vars, int num_mutable,
             int priority) {
  return static_cast<Engine *>(engine)->Push(fn, ctx, const_vars, num_const,
                                             mutable_vars, num_mutable,
                                             priority);
}

int mxe_push_ex(void *engine, mxe_fn_t fn, void *ctx, mxe_fn_t done_fn,
                void *done_ctx, const int64_t *const_vars, int num_const,
                const int64_t *mutable_vars, int num_mutable, int priority) {
  return static_cast<Engine *>(engine)->Push(fn, ctx, const_vars, num_const,
                                             mutable_vars, num_mutable,
                                             priority, done_fn, done_ctx);
}

int mxe_wait_for_var(void *engine, int64_t var) {
  return static_cast<Engine *>(engine)->WaitForVar(var);
}

void mxe_wait_all(void *engine) { static_cast<Engine *>(engine)->WaitAll(); }

int64_t mxe_pending(void *engine) {
  return static_cast<Engine *>(engine)->Pending();
}

}  // extern "C"
