/*
 * General C API (mxtpu_capi.h) — training-capable ABI for non-Python
 * frontends.
 *
 * Parity: include/mxnet/c_api.h + src/c_api/c_api.cc (reference).  The
 * reference implements these 115 functions over its C++ core; here the
 * core IS Python/JAX (symbol.py, executor.py, kvstore.py), so this layer
 * embeds CPython exactly like the predict ABI (c_predict.cc) and
 * delegates to mxnet_tpu._c_api_impl.  Handles are PyObject* owned
 * through refcounts; XLA executes everything behind simple_bind.
 *
 * Threading: every entry point takes the GIL (GilGuard); the ABI is
 * therefore safe to call from any host thread, serialized like the
 * reference's global lock in MXAPIThreadLocalEntry paths.
 */
#include "mxtpu_capi.h"

#include "py_embed.h"

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

using mxtpu_embed::EnsurePython;
using mxtpu_embed::Fail;
using mxtpu_embed::GilGuard;
using mxtpu_embed::last_error;

namespace {

PyObject *Impl() {
  static PyObject *impl = nullptr;  // leaked singleton, process lifetime
  if (!impl) impl = PyImport_ImportModule("mxnet_tpu._c_api_impl");
  return impl;
}

/* Call impl.<fn>(args...); returns new ref or nullptr (exception set).
 * CONSUMES args (every call site builds the tuple inline; leaking it
 * would pin the incref'd handles inside forever). */
PyObject *Call(const char *fn, PyObject *args) {
  PyObject *impl = Impl();
  if (!impl) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(impl, fn);
  if (!f) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  return r;
}

PyObject *ShapeList(const uint32_t *shape, uint32_t ndim) {
  PyObject *lst = PyList_New(ndim);
  for (uint32_t i = 0; i < ndim; ++i)
    PyList_SET_ITEM(lst, i, PyLong_FromUnsignedLong(shape[i]));
  return lst;
}

PyObject *StrList(const char **strs, uint32_t n) {
  PyObject *lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyUnicode_FromString(strs[i]));
  return lst;
}

/* CSR (ind_ptr/shape_data) -> list of shape lists */
PyObject *CsrShapes(uint32_t num, const uint32_t *ind_ptr,
                    const uint32_t *shape_data) {
  PyObject *lst = PyList_New(num);
  for (uint32_t i = 0; i < num; ++i) {
    uint32_t lo = ind_ptr[i], hi = ind_ptr[i + 1];
    PyList_SET_ITEM(lst, i, ShapeList(shape_data + lo, hi - lo));
  }
  return lst;
}

/* Per-handle string cache for the List* / SaveToJSON returns.  Keyed by
 * the handle; entries die with MX*Free. */
struct StrCache {
  std::vector<std::string> strings;
  std::vector<const char *> ptrs;
  std::string json;
};
std::unordered_map<void *, StrCache> &Caches() {
  static std::unordered_map<void *, StrCache> caches;
  return caches;
}

int ReturnStrList(void *handle, PyObject *list, uint32_t *out_size,
                  const char ***out_array, const char *where) {
  if (!list) return Fail(where);
  StrCache &c = Caches()[handle];
  c.strings.clear();
  c.ptrs.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i)
    c.strings.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(list, i)));
  for (auto &s : c.strings) c.ptrs.push_back(s.c_str());
  Py_DECREF(list);
  *out_size = static_cast<uint32_t>(n);
  *out_array = c.ptrs.data();
  return 0;
}

int FreeHandle(void *handle) {
  if (!handle) return 0;
  EnsurePython();
  GilGuard gil;
  Caches().erase(handle);
  Py_DECREF(reinterpret_cast<PyObject *>(handle));
  return 0;
}

/* thread-local InferShape result: [arg_shapes, out_shapes, aux_shapes] */
thread_local std::vector<std::vector<std::vector<uint32_t>>> infer_result;

}  // namespace

extern "C" {

const char *MXGetLastError(void) { return last_error.c_str(); }

int MXRandomSeed(int seed) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("random_seed", Py_BuildValue("(i)", seed));
  if (!r) return Fail("MXRandomSeed");
  Py_DECREF(r);
  return 0;
}

int MXNDArrayWaitAll(void) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("ndarray_wait_all", nullptr);
  if (!r) return Fail("MXNDArrayWaitAll");
  Py_DECREF(r);
  return 0;
}

/* ------------------------------------------------------------- NDArray */
int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim, int dev_type,
                    int dev_id, NDArrayHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *args = PyTuple_Pack(3, ShapeList(shape, ndim),
                                PyLong_FromLong(dev_type),
                                PyLong_FromLong(dev_id));
  /* PyTuple_Pack INCREFs; drop our refs */
  for (int i = 0; i < 3; ++i) Py_DECREF(PyTuple_GetItem(args, i));
  PyObject *r = Call("ndarray_create", args);  // Call consumes args
  if (!r) return Fail("MXNDArrayCreate");
  *out = r;  // ownership to caller
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) { return FreeHandle(handle); }

int MXNDArrayGetShape(NDArrayHandle handle, uint32_t *out_ndim,
                      uint32_t *shape_buf, uint32_t buf_cap) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("ndarray_shape",
                     Py_BuildValue("(O)", reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXNDArrayGetShape");
  Py_ssize_t n = PyList_Size(r);
  *out_ndim = static_cast<uint32_t>(n);
  for (Py_ssize_t i = 0; i < n && i < static_cast<Py_ssize_t>(buf_cap); ++i)
    shape_buf[i] = static_cast<uint32_t>(
        PyLong_AsUnsignedLong(PyList_GetItem(r, i)));
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const float *data,
                             uint64_t size) {
  EnsurePython();
  GilGuard gil;
  PyObject *mem = PyMemoryView_FromMemory(
      const_cast<char *>(reinterpret_cast<const char *>(data)),
      static_cast<Py_ssize_t>(size * sizeof(float)), PyBUF_READ);
  PyObject *r = Call("ndarray_sync_copy_from",
                     Py_BuildValue("(ON)",
                                   reinterpret_cast<PyObject *>(handle), mem));
  if (!r) return Fail("MXNDArraySyncCopyFromCPU");
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, float *data, uint64_t size) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("ndarray_sync_copy_to",
                     Py_BuildValue("(O)", reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXNDArraySyncCopyToCPU");
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &len) != 0) {
    Py_DECREF(r);
    return Fail("MXNDArraySyncCopyToCPU");
  }
  uint64_t want = size * sizeof(float);
  if (static_cast<uint64_t>(len) != want) {
    Py_DECREF(r);
    last_error = "MXNDArraySyncCopyToCPU: size mismatch";
    return -1;
  }
  std::memcpy(data, buf, want);
  Py_DECREF(r);
  return 0;
}

/* -------------------------------------------------------------- Symbol */
int MXSymbolListAtomicSymbolCreators(uint32_t *out_size,
                                     const char ***out_array) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("symbol_list_atomic_creators", nullptr);
  /* cache key: the function itself (stable) */
  return ReturnStrList(reinterpret_cast<void *>(
                           const_cast<char *>("atomic_creators")),
                       r, out_size, out_array,
                       "MXSymbolListAtomicSymbolCreators");
}

int MXSymbolCreateAtomicSymbol(const char *op, uint32_t num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("symbol_create_atomic",
                     Py_BuildValue("(sNN)", op, StrList(keys, num_param),
                                   StrList(vals, num_param)));
  if (!r) return Fail("MXSymbolCreateAtomicSymbol");
  *out = r;
  return 0;
}

int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("symbol_create_variable", Py_BuildValue("(s)", name));
  if (!r) return Fail("MXSymbolCreateVariable");
  *out = r;
  return 0;
}

int MXSymbolCompose(SymbolHandle sym, const char *name, uint32_t num_args,
                    const char **keys, SymbolHandle *args) {
  EnsurePython();
  GilGuard gil;
  PyObject *key_list = keys ? StrList(keys, num_args)
                            : (Py_INCREF(Py_None), Py_None);
  PyObject *arg_list = PyList_New(num_args);
  for (uint32_t i = 0; i < num_args; ++i) {
    PyObject *a = reinterpret_cast<PyObject *>(args[i]);
    Py_INCREF(a);
    PyList_SET_ITEM(arg_list, i, a);
  }
  PyObject *r = Call("symbol_compose",
                     Py_BuildValue("(OsNN)", reinterpret_cast<PyObject *>(sym),
                                   name ? name : "", key_list, arg_list));
  if (!r) return Fail("MXSymbolCompose");
  Py_DECREF(r);  // compose mutates sym in place
  return 0;
}

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("symbol_from_json", Py_BuildValue("(s)", json));
  if (!r) return Fail("MXSymbolCreateFromJSON");
  *out = r;
  return 0;
}

int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("symbol_to_json",
                     Py_BuildValue("(O)", reinterpret_cast<PyObject *>(sym)));
  if (!r) return Fail("MXSymbolSaveToJSON");
  StrCache &c = Caches()[sym];
  c.json = PyUnicode_AsUTF8(r);
  Py_DECREF(r);
  *out_json = c.json.c_str();
  return 0;
}

#define LIST_FN(CNAME, PYNAME)                                              \
  int CNAME(SymbolHandle sym, uint32_t *out_size, const char ***out_array) { \
    EnsurePython();                                                         \
    GilGuard gil;                                                           \
    PyObject *r = Call(PYNAME, Py_BuildValue(                               \
        "(O)", reinterpret_cast<PyObject *>(sym)));                         \
    return ReturnStrList(sym, r, out_size, out_array, #CNAME);              \
  }

LIST_FN(MXSymbolListArguments, "symbol_list_arguments")
LIST_FN(MXSymbolListOutputs, "symbol_list_outputs")
LIST_FN(MXSymbolListAuxiliaryStates, "symbol_list_auxiliary_states")
#undef LIST_FN

int MXSymbolInferShape(SymbolHandle sym, uint32_t num_known,
                       const char **keys, const uint32_t *arg_ind_ptr,
                       const uint32_t *arg_shape_data, uint32_t *arg_count,
                       uint32_t *out_count, uint32_t *aux_count) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("symbol_infer_shape",
                     Py_BuildValue("(ONN)", reinterpret_cast<PyObject *>(sym),
                                   StrList(keys, num_known),
                                   CsrShapes(num_known, arg_ind_ptr,
                                             arg_shape_data)));
  if (!r) return Fail("MXSymbolInferShape");
  infer_result.assign(3, {});
  for (int g = 0; g < 3; ++g) {
    PyObject *group = PyTuple_GetItem(r, g);
    Py_ssize_t n = PyList_Size(group);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *shp = PyList_GetItem(group, i);
      std::vector<uint32_t> dims;
      for (Py_ssize_t d = 0; d < PyList_Size(shp); ++d)
        dims.push_back(static_cast<uint32_t>(
            PyLong_AsUnsignedLong(PyList_GetItem(shp, d))));
      infer_result[g].push_back(std::move(dims));
    }
  }
  Py_DECREF(r);
  *arg_count = static_cast<uint32_t>(infer_result[0].size());
  *out_count = static_cast<uint32_t>(infer_result[1].size());
  *aux_count = static_cast<uint32_t>(infer_result[2].size());
  return 0;
}

int MXSymbolInferShapeGet(int which, uint32_t index, uint32_t *out_ndim,
                          uint32_t *shape_buf, uint32_t buf_cap) {
  if (which < 0 || which > 2 || infer_result.size() != 3 ||
      index >= infer_result[static_cast<size_t>(which)].size()) {
    last_error = "MXSymbolInferShapeGet: no InferShape result on this "
                 "thread or index out of range";
    return -1;
  }
  auto &dims = infer_result[static_cast<size_t>(which)][index];
  *out_ndim = static_cast<uint32_t>(dims.size());
  for (uint32_t i = 0; i < dims.size() && i < buf_cap; ++i)
    shape_buf[i] = dims[i];
  return 0;
}

int MXSymbolFree(SymbolHandle sym) { return FreeHandle(sym); }

/* ------------------------------------------------------------ Executor */
int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         const char *grad_req, uint32_t num_args,
                         const char **keys, const uint32_t *arg_ind_ptr,
                         const uint32_t *arg_shape_data,
                         ExecutorHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("executor_simple_bind",
                     Py_BuildValue("(OiisNN)",
                                   reinterpret_cast<PyObject *>(sym),
                                   dev_type, dev_id, grad_req,
                                   StrList(keys, num_args),
                                   CsrShapes(num_args, arg_ind_ptr,
                                             arg_shape_data)));
  if (!r) return Fail("MXExecutorSimpleBind");
  *out = r;
  return 0;
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("executor_forward",
                     Py_BuildValue("(Oi)",
                                   reinterpret_cast<PyObject *>(handle),
                                   is_train));
  if (!r) return Fail("MXExecutorForward");
  Py_DECREF(r);
  return 0;
}

int MXExecutorBackward(ExecutorHandle handle) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("executor_backward",
                     Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXExecutorBackward");
  Py_DECREF(r);
  return 0;
}

int MXExecutorNumOutputs(ExecutorHandle handle, uint32_t *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("executor_num_outputs",
                     Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXExecutorNumOutputs");
  *out = static_cast<uint32_t>(PyLong_AsUnsignedLong(r));
  Py_DECREF(r);
  return 0;
}

namespace {
/* Executor NDArray lookups return OWNED handles (the Python side may
 * construct a fresh wrapper per call); the caller frees each with
 * MXNDArrayFree.  The underlying buffer stays shared with the executor,
 * so writes through the handle are visible to subsequent forwards. */
int ExecLookup(const char *pyfn, ExecutorHandle handle, PyObject *arg2,
               NDArrayHandle *out, const char *where) {
  PyObject *r = Call(pyfn, Py_BuildValue(
      "(ON)", reinterpret_cast<PyObject *>(handle), arg2));
  if (!r) return Fail(where);
  *out = r;
  return 0;
}
}  // namespace

int MXExecutorOutput(ExecutorHandle handle, uint32_t index,
                     NDArrayHandle *out) {
  EnsurePython();
  GilGuard gil;
  return ExecLookup("executor_output", handle,
                    PyLong_FromUnsignedLong(index), out, "MXExecutorOutput");
}

int MXExecutorArgArray(ExecutorHandle handle, const char *name,
                       NDArrayHandle *out) {
  EnsurePython();
  GilGuard gil;
  return ExecLookup("executor_arg_array", handle,
                    PyUnicode_FromString(name), out, "MXExecutorArgArray");
}

int MXExecutorGradArray(ExecutorHandle handle, const char *name,
                        NDArrayHandle *out) {
  EnsurePython();
  GilGuard gil;
  return ExecLookup("executor_grad_array", handle,
                    PyUnicode_FromString(name), out, "MXExecutorGradArray");
}

int MXExecutorFree(ExecutorHandle handle) { return FreeHandle(handle); }

/* ------------------------------------------------------------- KVStore */
int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("kvstore_create", Py_BuildValue("(s)", type));
  if (!r) return Fail("MXKVStoreCreate");
  *out = r;
  return 0;
}

int MXKVStoreFree(KVStoreHandle handle) { return FreeHandle(handle); }

namespace {
PyObject *IntList(const int *keys, uint32_t n) {
  PyObject *lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyLong_FromLong(keys[i]));
  return lst;
}

PyObject *HandleList(NDArrayHandle *vals, uint32_t n) {
  PyObject *lst = PyList_New(n);
  for (uint32_t i = 0; i < n; ++i) {
    PyObject *v = reinterpret_cast<PyObject *>(vals[i]);
    Py_INCREF(v);
    PyList_SET_ITEM(lst, i, v);
  }
  return lst;
}
}  // namespace

int MXKVStoreInit(KVStoreHandle handle, uint32_t num, const int *keys,
                  NDArrayHandle *vals) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("kvstore_init",
                     Py_BuildValue("(ONN)",
                                   reinterpret_cast<PyObject *>(handle),
                                   IntList(keys, num), HandleList(vals, num)));
  if (!r) return Fail("MXKVStoreInit");
  Py_DECREF(r);
  return 0;
}

int MXKVStorePush(KVStoreHandle handle, uint32_t num, const int *keys,
                  NDArrayHandle *vals, int priority) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("kvstore_push",
                     Py_BuildValue("(ONNi)",
                                   reinterpret_cast<PyObject *>(handle),
                                   IntList(keys, num), HandleList(vals, num),
                                   priority));
  if (!r) return Fail("MXKVStorePush");
  Py_DECREF(r);
  return 0;
}

int MXKVStorePull(KVStoreHandle handle, uint32_t num, const int *keys,
                  NDArrayHandle *outs, int priority) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("kvstore_pull",
                     Py_BuildValue("(ONNi)",
                                   reinterpret_cast<PyObject *>(handle),
                                   IntList(keys, num), HandleList(outs, num),
                                   priority));
  if (!r) return Fail("MXKVStorePull");
  Py_DECREF(r);
  return 0;
}

namespace {
/* Trampoline: a PyCFunction whose capsule self carries the C updater. */
struct UpdaterClosure {
  MXKVStoreUpdater fn;
  void *handle;
};

PyObject *UpdaterTrampoline(PyObject *self, PyObject *args) {
  auto *cl = static_cast<UpdaterClosure *>(
      PyCapsule_GetPointer(self, "mxtpu.updater"));
  int key = 0;
  PyObject *recv = nullptr, *local = nullptr;
  if (!PyArg_ParseTuple(args, "iOO", &key, &recv, &local)) return nullptr;
  /* release the GIL? no: the C updater will call back into the ABI,
   * which re-acquires; keeping it held avoids a handoff race. */
  cl->fn(key, recv, local, cl->handle);
  Py_RETURN_NONE;
}

PyMethodDef updater_def = {"mxtpu_updater", UpdaterTrampoline, METH_VARARGS,
                           "C kvstore updater trampoline"};

void UpdaterCapsuleFree(PyObject *cap) {
  delete static_cast<UpdaterClosure *>(
      PyCapsule_GetPointer(cap, "mxtpu.updater"));
}
}  // namespace

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle) {
  EnsurePython();
  GilGuard gil;
  auto *cl = new UpdaterClosure{updater, updater_handle};
  PyObject *cap = PyCapsule_New(cl, "mxtpu.updater", UpdaterCapsuleFree);
  PyObject *fn = PyCFunction_New(&updater_def, cap);
  Py_DECREF(cap);  // fn owns it now
  PyObject *r = Call("kvstore_set_updater",
                     Py_BuildValue("(ON)",
                                   reinterpret_cast<PyObject *>(handle), fn));
  if (!r) return Fail("MXKVStoreSetUpdater");
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetRank(KVStoreHandle handle, int *rank) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("kvstore_rank",
                     Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXKVStoreGetRank");
  *rank = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("kvstore_num_workers",
                     Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXKVStoreGetGroupSize");
  *size = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXImperativeInvoke(const char *op, uint32_t num_inputs,
                       NDArrayHandle *inputs, uint32_t num_params,
                       const char **keys, const char **vals,
                       uint32_t out_capacity, uint32_t *num_outputs,
                       NDArrayHandle *outputs) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("imperative_invoke",
                     Py_BuildValue("(sNNN)", op,
                                   HandleList(inputs, num_inputs),
                                   StrList(keys, num_params),
                                   StrList(vals, num_params)));
  if (!r) return Fail("MXImperativeInvoke");
  Py_ssize_t n = PyList_Size(r);
  if (static_cast<uint32_t>(n) > out_capacity) {
    Py_DECREF(r);
    last_error = "MXImperativeInvoke: output buffer too small";
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(r, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  *num_outputs = static_cast<uint32_t>(n);
  Py_DECREF(r);
  return 0;
}

int MXListDataIters(uint32_t *out_size, const char ***out_names) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("list_data_iters", PyTuple_New(0));
  /* cached under a process-stable key (nullptr handle slot) */
  return ReturnStrList(nullptr, r, out_size, out_names, "MXListDataIters");
}

int MXDataIterCreateIter(const char *name, uint32_t num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("data_iter_create",
                     Py_BuildValue("(sNN)", name, StrList(keys, num_param),
                                   StrList(vals, num_param)));
  if (!r) return Fail("MXDataIterCreateIter");
  *out = r;
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("data_iter_next",
                     Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXDataIterNext");
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("data_iter_before_first",
                     Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXDataIterBeforeFirst");
  Py_DECREF(r);
  return 0;
}

namespace {
int IterPart(const char *fn, const char *where, DataIterHandle handle,
             NDArrayHandle *out) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call(fn, Py_BuildValue(
      "(O)", reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail(where);
  *out = r;  // new NDArray handle, caller frees
  return 0;
}
}  // namespace

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out) {
  return IterPart("data_iter_data", "MXDataIterGetData", handle, out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out) {
  return IterPart("data_iter_label", "MXDataIterGetLabel", handle, out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int *pad) {
  EnsurePython();
  GilGuard gil;
  PyObject *r = Call("data_iter_pad",
                     Py_BuildValue("(O)",
                                   reinterpret_cast<PyObject *>(handle)));
  if (!r) return Fail("MXDataIterGetPadNum");
  *pad = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXDataIterFree(DataIterHandle handle) { return FreeHandle(handle); }

}  // extern "C"
