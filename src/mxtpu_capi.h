/*
 * mxtpu general C API — the training-capable ABI for non-Python
 * frontends (parity: include/mxnet/c_api.h, the 115-function surface
 * SURVEY.md App B calls "the real product"; this is the subset language
 * bindings actually consume: NDArray lifecycle, symbol composition,
 * executor bind/forward/backward, kvstore init/push/pull/updater).
 *
 * Conventions (same as the reference):
 *   - every function returns 0 on success, -1 on failure;
 *     MXGetLastError() returns the failure text (thread-local)
 *   - handles are opaque; free NDArray/Symbol/Executor/KVStore handles
 *     with their MX*Free call exactly once
 *   - dev_type: 1 = cpu, 2 = accelerator (tpu), as in the predict ABI
 *   - all tensor data crosses this ABI as float32 (the reference's
 *     default real_t; mixed precision stays on-device)
 *
 * List-returning calls (ListArguments etc.) and SaveToJSON return
 * pointers owned by the library, valid until the next call ON THE SAME
 * HANDLE; copy out if you need them longer.  InferShape results are
 * thread-local, valid until the next MXSymbolInferShape on that thread.
 */
#ifndef MXTPU_CAPI_H_
#define MXTPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *KVStoreHandle;
typedef void *DataIterHandle;

const char *MXGetLastError(void);
int MXRandomSeed(int seed);
/* Block until all queued work has completed (parity: MXNDArrayWaitAll). */
int MXNDArrayWaitAll(void);

/* ----------------------------------------------------------- NDArray */
int MXNDArrayCreate(const uint32_t *shape, uint32_t ndim, int dev_type,
                    int dev_id, NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
/* Writes ndim to *out_ndim and up to buf_cap dims into shape_buf. */
int MXNDArrayGetShape(NDArrayHandle handle, uint32_t *out_ndim,
                      uint32_t *shape_buf, uint32_t buf_cap);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const float *data,
                             uint64_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, float *data, uint64_t size);

/* ------------------------------------------------------------ Symbol */
int MXSymbolListAtomicSymbolCreators(uint32_t *out_size,
                                     const char ***out_array);
/* Atomic symbol = op name + string attrs; fill inputs with Compose. */
int MXSymbolCreateAtomicSymbol(const char *op, uint32_t num_param,
                               const char **keys, const char **vals,
                               SymbolHandle *out);
int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
/* keys NULL = positional args.  Mutates sym in place (reference
 * semantics: nnvm Symbol::Compose). */
int MXSymbolCompose(SymbolHandle sym, const char *name, uint32_t num_args,
                    const char **keys, SymbolHandle *args);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolSaveToJSON(SymbolHandle sym, const char **out_json);
int MXSymbolListArguments(SymbolHandle sym, uint32_t *out_size,
                          const char ***out_array);
int MXSymbolListOutputs(SymbolHandle sym, uint32_t *out_size,
                        const char ***out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, uint32_t *out_size,
                                const char ***out_array);
/* Known input shapes as CSR (keys / ind_ptr / shape_data, like the
 * reference); result counts via out params, then fetch each shape with
 * MXSymbolInferShapeGet(which: 0=args 1=outputs 2=aux). */
int MXSymbolInferShape(SymbolHandle sym, uint32_t num_known,
                       const char **keys, const uint32_t *arg_ind_ptr,
                       const uint32_t *arg_shape_data, uint32_t *arg_count,
                       uint32_t *out_count, uint32_t *aux_count);
int MXSymbolInferShapeGet(int which, uint32_t index, uint32_t *out_ndim,
                          uint32_t *shape_buf, uint32_t buf_cap);
int MXSymbolFree(SymbolHandle sym);

/* ---------------------------------------------------------- Executor */
/* grad_req: "write", "add" or "null".  Input shapes as CSR like
 * InferShape.  (parity: MXExecutorSimpleBind; memory planning is XLA's.) */
int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         const char *grad_req, uint32_t num_args,
                         const char **keys, const uint32_t *arg_ind_ptr,
                         const uint32_t *arg_shape_data,
                         ExecutorHandle *out);
int MXExecutorForward(ExecutorHandle handle, int is_train);
/* Head gradient = ones (the training path through MakeLoss/SoftmaxOutput,
 * same default as the reference's Backward with no ograds). */
int MXExecutorBackward(ExecutorHandle handle);
int MXExecutorNumOutputs(ExecutorHandle handle, uint32_t *out);
/* Output/Arg/Grad lookups return OWNED handles: free each with
 * MXNDArrayFree.  The buffer stays shared with the executor, so writes
 * through an arg handle feed the next Forward. */
int MXExecutorOutput(ExecutorHandle handle, uint32_t index,
                     NDArrayHandle *out);
int MXExecutorArgArray(ExecutorHandle handle, const char *name,
                       NDArrayHandle *out);
int MXExecutorGradArray(ExecutorHandle handle, const char *name,
                        NDArrayHandle *out);
int MXExecutorFree(ExecutorHandle handle);

/* ----------------------------------------------------------- KVStore */
int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, uint32_t num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, uint32_t num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle handle, uint32_t num, const int *keys,
                  NDArrayHandle *outs, int priority);
/* updater(key, recv_grad, local_weight, updater_handle) runs for every
 * pushed key (parity: MXKVStoreSetUpdater).  recv/local are borrowed. */
typedef void (*MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                 NDArrayHandle local, void *updater_handle);
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreGetRank(KVStoreHandle handle, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size);

/* Imperative op entry (parity: MXImperativeInvoke, c_api_ndarray.cc:19).
 * Runs a registered op on input NDArrays with string attrs; writes up to
 * out_capacity new output handles and their count. */
int MXImperativeInvoke(const char *op, uint32_t num_inputs,
                       NDArrayHandle *inputs, uint32_t num_params,
                       const char **keys, const char **vals,
                       uint32_t out_capacity, uint32_t *num_outputs,
                       NDArrayHandle *outputs);

/* Data iterators (parity: MXListDataIters / MXDataIterCreateIter family).
 * Iterators are created by registry name (MNISTIter, CSVIter,
 * ImageRecordIter) with string kwargs, exactly like the reference's
 * dmlc::Parameter-driven C iterators.  GetData/GetLabel return NEW
 * NDArray handles (free with MXNDArrayFree). */
int MXListDataIters(uint32_t *out_size, const char ***out_names);
int MXDataIterCreateIter(const char *name, uint32_t num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);
int MXDataIterFree(DataIterHandle handle);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_CAPI_H_ */
